"""Elastic-runtime unit tests (docs/fault_tolerance.md, "Surviving host
loss"): heartbeat health plane, collective watchdog, cohort re-formation.

Everything here is tier-1 fast: the heartbeat halves run in-process with
millisecond intervals, the watchdog uses injectable ``on_timeout``/
``exit_fn``, and the cohort supervisor drives throwaway *stdlib* child
scripts (no paddle import per child) exactly like test_elastic_launch.py.
The end-to-end chaos proof (real 2-process training job, kill + hang +
bit-identical resume) lives in tests/test_elastic_cohort.py (slow lane).
"""
import os
import textwrap
import time

import pytest

from paddle_tpu.core.monitor import StatRegistry, default_registry
from paddle_tpu.distributed.elastic import (DIVERGENCE_EXIT_CODE,
                                            HOST_LOST_EXIT_CODE,
                                            PREEMPTION_EXIT_CODE)
from paddle_tpu.distributed.elastic_runtime import (
    COHORT_GEN_VAR, HEARTBEAT_ADDR_VAR, STEP_DEADLINE_VAR, BeaconSender,
    CohortSupervisor, HeartbeatConfig, HeartbeatCoordinator, HeartbeatPlane,
    StepWatchdog, cohort_generation, maybe_auto_sender, maybe_auto_watchdog)
from paddle_tpu.distributed.elastic_runtime import heartbeat as hb_mod
from paddle_tpu.distributed.elastic_runtime import watchdog as wd_mod
from paddle_tpu.observability import flight
from paddle_tpu.utils.resilience import (FAULT_CRASH_EXIT_CODE,
                                         _reset_fault_injector_for_tests)

FAST = dict(interval_s=0.03, miss_threshold=3)


def _wait(pred, timeout_s=5.0, poll_s=0.005):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(poll_s)
    return pred()


def _events_since(n, kind=None):
    evs = flight.default_recorder().events()[n:]
    if kind is None:
        return evs
    return [e for e in evs if e["kind"] == kind]


@pytest.fixture
def clean_faults(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_FAULT_SPEC", raising=False)
    _reset_fault_injector_for_tests()
    yield monkeypatch
    _reset_fault_injector_for_tests()


class TestHeartbeatPlane:
    def test_registration_snapshot_and_gauges(self):
        reg = StatRegistry()
        with HeartbeatCoordinator(config=HeartbeatConfig(**FAST),
                                  registry=reg) as coord:
            with BeaconSender(coord.address, rank=0,
                              config=HeartbeatConfig(**FAST)) as sender:
                sender.notify_step(7, 0.012)
                assert _wait(lambda: coord.snapshot().get(0, {})
                             .get("step") == 7)
                snap = coord.snapshot()[0]
                assert snap["pid"] == os.getpid()
                assert snap["dead"] is False
                assert snap["step_s"] == pytest.approx(0.012)
        assert reg.labeled("distributed.host_up")[(("rank", "0"),)] == 1.0
        assert reg.labeled("distributed.host_step")[(("rank", "0"),)] == 7.0
        assert reg.get("distributed.heartbeats") >= 1

    def test_death_declared_with_flight_event_before_callback(self):
        reg = StatRegistry()
        cfg = HeartbeatConfig(**FAST)
        n0 = len(flight.default_recorder().events())
        event_first = []

        def on_death(rank, info):
            # acceptance contract: the distributed.host_lost flight event
            # must already be recorded when teardown (this callback) runs
            event_first.append(
                bool(_events_since(n0, "distributed.host_lost")))

        with HeartbeatCoordinator(config=cfg, on_death=on_death,
                                  registry=reg) as coord:
            sender = BeaconSender(coord.address, rank=3, config=cfg).start()
            assert _wait(lambda: 3 in coord.snapshot())
            t0 = time.monotonic()
            sender.stop()
            assert _wait(lambda: 3 in coord.declared_dead())
            detect = time.monotonic() - t0
            assert detect < cfg.death_after_s + 10 * cfg.interval_s + 1.0
            info = coord.declared_dead()[3]
            assert info["rank"] == 3
            assert info["silent_s"] > cfg.death_after_s
        assert event_first == [True]
        evs = _events_since(n0, "distributed.host_lost")
        assert evs and evs[0]["rank"] == 3
        assert reg.labeled("distributed.host_up")[(("rank", "3"),)] == 0.0
        assert reg.get("distributed.deaths_declared") == 1

    def test_recovery_after_false_declaration(self):
        cfg = HeartbeatConfig(**FAST)
        n0 = len(flight.default_recorder().events())
        with HeartbeatCoordinator(config=cfg,
                                  registry=StatRegistry()) as coord:
            s1 = BeaconSender(coord.address, rank=1, config=cfg).start()
            assert _wait(lambda: 1 in coord.snapshot())
            s1.stop()
            assert _wait(lambda: 1 in coord.declared_dead())
            # the "dead" host beacons again: partition, not death
            with BeaconSender(coord.address, rank=1, config=cfg):
                assert _wait(lambda: 1 not in coord.declared_dead())
        assert _events_since(n0, "distributed.host_recovered")

    def test_peer_death_propagates_in_beacon_reply(self):
        cfg = HeartbeatConfig(**FAST)
        with HeartbeatCoordinator(config=cfg,
                                  registry=StatRegistry()) as coord:
            with BeaconSender(coord.address, rank=0, config=cfg) as survivor:
                victim = BeaconSender(coord.address, rank=1,
                                      config=cfg).start()
                assert _wait(lambda: 1 in coord.snapshot())
                victim.stop()
                assert _wait(lambda: 1 in survivor.peer_dead)

    def test_straggler_rising_edge_event_and_gauge(self):
        reg = StatRegistry()
        cfg = HeartbeatConfig(straggler_z=1.5, straggler_min_peers=4, **FAST)
        n0 = len(flight.default_recorder().events())
        with HeartbeatCoordinator(config=cfg, registry=reg) as coord:
            senders = [BeaconSender(coord.address, rank=r,
                                    config=cfg).start() for r in range(4)]
            try:
                for r, s in enumerate(senders):
                    s.notify_step(10, 10.0 if r == 3 else 0.01)
                assert _wait(lambda: reg.labeled("distributed.straggler")
                             .get((("rank", "3"),)) == 1.0)
                assert reg.labeled(
                    "distributed.straggler")[(("rank", "0"),)] == 0.0
                evs = _events_since(n0, "distributed.straggler")
                assert evs and evs[0]["rank"] == 3 and evs[0]["z"] > 1.5
                # rising edge only: staying slow emits no second event
                time.sleep(4 * cfg.interval_s)
                assert len(_events_since(
                    n0, "distributed.straggler")) == len(evs)
            finally:
                for s in senders:
                    s.stop()

    def test_sender_declares_coordinator_lost(self):
        cfg = HeartbeatConfig(interval_s=0.03, miss_threshold=2)
        n0 = len(flight.default_recorder().events())
        coord = HeartbeatCoordinator(config=cfg, registry=StatRegistry())
        coord.start()
        lost = []
        sender = BeaconSender(coord.address, rank=0, config=cfg,
                              on_coordinator_lost=lambda: lost.append(1))
        sender.start()
        try:
            assert _wait(lambda: 0 in coord.snapshot())
            coord.stop()  # the control plane vanishes, the worker survives
            assert _wait(lambda: sender.coordinator_lost)
            assert lost == [1]
            evs = _events_since(n0, "distributed.coordinator_lost")
            assert evs and evs[0]["consecutive_failures"] \
                >= cfg.miss_threshold
        finally:
            sender.stop()
            coord.stop()

    def test_set_generation_wipes_declarations(self):
        cfg = HeartbeatConfig(**FAST)
        with HeartbeatCoordinator(config=cfg,
                                  registry=StatRegistry()) as coord:
            s = BeaconSender(coord.address, rank=2, config=cfg).start()
            assert _wait(lambda: 2 in coord.snapshot())
            s.stop()
            assert _wait(lambda: 2 in coord.declared_dead())
            coord.set_generation(1)
            assert coord.declared_dead() == {}
            assert coord.snapshot() == {}
            assert coord.generation == 1

    def test_metricsz_renders_labeled_heartbeat_gauges(self):
        from paddle_tpu.observability.metrics import render_prometheus
        cfg = HeartbeatConfig(**FAST)
        with HeartbeatCoordinator(config=cfg) as coord:  # default registry
            with BeaconSender(coord.address, rank=0, config=cfg):
                assert _wait(lambda: 0 in coord.snapshot())
        text = render_prometheus(default_registry())
        assert 'host_up{rank="0"}' in text

    def test_cohort_generation_env_parse(self, monkeypatch):
        monkeypatch.delenv(COHORT_GEN_VAR, raising=False)
        assert cohort_generation() == 0
        monkeypatch.setenv(COHORT_GEN_VAR, "4")
        assert cohort_generation() == 4
        monkeypatch.setenv(COHORT_GEN_VAR, "junk")
        assert cohort_generation() == 0

    def test_facade_names_the_halves(self):
        assert HeartbeatPlane.coordinator is HeartbeatCoordinator
        assert HeartbeatPlane.sender is BeaconSender


class TestHeartbeatFaultSites:
    def test_heartbeat_partition_latches_until_declared(self, clean_faults):
        clean_faults.setenv("PADDLE_TPU_FAULT_SPEC",
                            "heartbeat_partition:3:drop")
        _reset_fault_injector_for_tests()
        cfg = HeartbeatConfig(**FAST)
        n0 = len(flight.default_recorder().events())
        with HeartbeatCoordinator(config=cfg,
                                  registry=StatRegistry()) as coord:
            with BeaconSender(coord.address, rank=0, config=cfg):
                assert _wait(lambda: 0 in coord.snapshot())
                # the 3rd beat latches the partition; the sender process is
                # alive the whole time yet gets declared dead
                assert _wait(lambda: 0 in coord.declared_dead())
        assert _events_since(n0, "distributed.host_lost")

    def test_slow_link_blip_is_not_a_death(self, clean_faults, monkeypatch):
        clean_faults.setenv("PADDLE_TPU_FAULT_SPEC", "slow_link:2:delay")
        _reset_fault_injector_for_tests()
        monkeypatch.setattr(hb_mod, "SLOW_LINK_SECONDS", 0.05)
        cfg = HeartbeatConfig(**FAST)  # death after 0.09s silence
        deaths = []
        with HeartbeatCoordinator(config=cfg, registry=StatRegistry(),
                                  on_death=lambda r, i: deaths.append(r)) \
                as coord:
            with BeaconSender(coord.address, rank=0, config=cfg):
                assert _wait(lambda: 0 in coord.snapshot())
                time.sleep(0.05 + 3 * cfg.death_after_s)
            assert deaths == []


class TestStepWatchdog:
    def test_rejects_non_positive_deadline(self):
        with pytest.raises(ValueError, match="deadline must be positive"):
            StepWatchdog(0.0)
        with pytest.raises(ValueError, match="deadline must be positive"):
            StepWatchdog(-1.0)

    def test_disarm_returns_step_wall_time(self):
        wd = StepWatchdog(60.0)
        try:
            assert wd.disarm() is None  # unarmed: a no-op, not an error
            wd.arm(0)
            assert wd.armed
            time.sleep(0.02)
            elapsed = wd.disarm()
            assert elapsed >= 0.02
            assert not wd.armed and not wd.fired
        finally:
            wd.stop()

    def test_fires_on_timeout_with_flight_event(self):
        fired = []
        n0 = len(flight.default_recorder().events())
        wd = StepWatchdog(0.05, on_timeout=lambda s, e: fired.append((s, e)))
        try:
            wd.arm(9)
            assert _wait(lambda: wd.fired)
            assert not wd.armed  # the hung step was consumed
            step, elapsed = fired[0]
            assert step == 9 and elapsed > 0.05
            evs = _events_since(n0, "distributed.watchdog_fired")
            assert evs and evs[0]["step"] == 9
            assert evs[0]["deadline_s"] == pytest.approx(0.05)
        finally:
            wd.stop()

    def test_exit_path_dumps_flight_record(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        codes = []
        wd = StepWatchdog(0.05, exit_fn=codes.append)
        try:
            wd.arm(2)
            assert _wait(lambda: codes)
            assert codes == [HOST_LOST_EXIT_CODE]
            dumps = [p for p in os.listdir(tmp_path)
                     if p.startswith("flight_")]
            assert dumps, "the terminal path must dump before exiting"
        finally:
            wd.stop()

    def test_guard_context_manager_and_heartbeat_wiring(self):
        seen = []

        class FakeSender:
            def notify_step(self, step, step_s):
                seen.append((step, step_s))

        wd = StepWatchdog(60.0, heartbeat=FakeSender())
        try:
            with wd.guard(5):
                assert wd.armed
            assert not wd.armed
            assert seen and seen[0][0] == 5 and seen[0][1] >= 0.0
        finally:
            wd.stop()

    def test_host_kill_site_hard_exits(self, clean_faults, monkeypatch):
        clean_faults.setenv("PADDLE_TPU_FAULT_SPEC", "host_kill:2:crash")
        _reset_fault_injector_for_tests()
        exits = []
        monkeypatch.setattr(os, "_exit", lambda code: exits.append(code))
        wd = StepWatchdog(60.0)
        try:
            wd.arm(0)
            wd.disarm()
            assert exits == []
            wd.arm(1)  # the 2nd guarded step is where the host "dies"
            assert exits == [FAULT_CRASH_EXIT_CODE]
        finally:
            wd.stop()

    def test_collective_hang_site_is_caught_by_deadline(self, clean_faults,
                                                        monkeypatch):
        clean_faults.setenv("PADDLE_TPU_FAULT_SPEC", "collective_hang:1:hang")
        _reset_fault_injector_for_tests()
        monkeypatch.setattr(wd_mod, "HANG_SECONDS", 0.3)
        fired = []
        wd = StepWatchdog(0.08, on_timeout=lambda s, e: fired.append(s))
        try:
            t0 = time.monotonic()
            wd.arm(0)  # blocks inside the armed window for HANG_SECONDS
            hung = time.monotonic() - t0
            assert hung >= 0.3
            assert _wait(lambda: fired == [0])
        finally:
            wd.stop()


class TestAutoWiring:
    @pytest.fixture(autouse=True)
    def _clean(self, monkeypatch):
        monkeypatch.delenv(STEP_DEADLINE_VAR, raising=False)
        monkeypatch.delenv(HEARTBEAT_ADDR_VAR, raising=False)
        wd_mod._reset_auto_watchdog_for_tests()
        hb_mod._reset_auto_sender_for_tests()
        yield monkeypatch
        wd_mod._reset_auto_watchdog_for_tests()
        hb_mod._reset_auto_sender_for_tests()

    def test_no_env_no_watchdog(self):
        assert maybe_auto_watchdog() is None
        assert maybe_auto_sender() is None

    def test_explicit_instance_wins(self):
        wd = StepWatchdog(5.0)
        try:
            assert maybe_auto_watchdog(wd) is wd
        finally:
            wd.stop()

    def test_env_contract_arms_singleton(self, _clean):
        _clean.setenv(STEP_DEADLINE_VAR, "2.5")
        wd = maybe_auto_watchdog()
        assert wd is not None and wd.deadline_s == 2.5
        assert maybe_auto_watchdog() is wd  # idempotent

    def test_bad_or_zero_deadline_means_off(self, _clean):
        _clean.setenv(STEP_DEADLINE_VAR, "0")
        assert maybe_auto_watchdog() is None
        _clean.setenv(STEP_DEADLINE_VAR, "nope")
        assert maybe_auto_watchdog() is None

    def test_heartbeat_addr_arms_sender_with_rank(self, _clean):
        cfg = HeartbeatConfig(**FAST)
        with HeartbeatCoordinator(config=cfg,
                                  registry=StatRegistry()) as coord:
            _clean.setenv(HEARTBEAT_ADDR_VAR, coord.address)
            _clean.setenv("PADDLE_TRAINER_ID", "1")
            _clean.setenv(STEP_DEADLINE_VAR, "3.0")
            sender = maybe_auto_sender()
            assert sender is not None and sender.rank == 1
            # the auto watchdog picks up the auto sender so step times
            # flow to the straggler detector with zero explicit wiring
            wd = maybe_auto_watchdog()
            assert wd.heartbeat is sender
            assert _wait(lambda: 1 in coord.snapshot())


# ---------------------------------------------------------------------------
# Cohort supervisor: stdlib child scripts, in-process run loop.
# ---------------------------------------------------------------------------

def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def _cohort(script, endpoints=("127.0.0.1:7101", "127.0.0.1:7102"), **kw):
    kw.setdefault("max_restarts", 3)
    kw.setdefault("grace_period", 3.0)
    kw.setdefault("restart_backoff", 0.02)
    kw.setdefault("settle_s", 0.3)
    sup = CohortSupervisor(list(endpoints), script, [], **kw)
    sup.poll_interval = 0.05
    return sup


class TestCohortSupervisor:
    def test_exit_121_reforms_whole_cohort(self, tmp_path, capsys):
        n0 = len(flight.default_recorder().events())
        script = _write(tmp_path, "child.py", f"""
            import os, sys, time
            gen = os.environ["{COHORT_GEN_VAR}"]
            rank = os.environ["PADDLE_TRAINER_ID"]
            if gen == "0":
                if rank == "0":
                    sys.exit({HOST_LOST_EXIT_CODE})  # watchdog messenger
                time.sleep(60)  # the survivor, wedged in a collective
            open(os.path.join({str(tmp_path)!r}, f"done_{{rank}}_{{gen}}"),
                 "w").write(os.environ["PADDLE_TRAINERS_NUM"])
            sys.exit(0)
        """)
        sup = _cohort(script)
        rc = sup.run()
        assert rc == 0
        assert sup.generation == 1 and sup.reforms == 1
        assert sup.restarts_used == 1  # one budget unit for the reform
        for rank in (0, 1):
            p = tmp_path / f"done_{rank}_1"
            assert p.exists() and p.read_text() == "2"
        evs = _events_since(n0, "distributed.cohort_reform")
        assert evs and evs[0]["next_gen"] == 1
        assert "re-forming" in capsys.readouterr().err

    def test_fatal_crash_in_multirank_world_reforms(self, tmp_path):
        script = _write(tmp_path, "child.py", f"""
            import os, sys, time
            if os.environ["{COHORT_GEN_VAR}"] == "0":
                sys.exit(9) if os.environ["PADDLE_TRAINER_ID"] == "1" \\
                    else time.sleep(60)
            open(os.path.join({str(tmp_path)!r},
                 "gen1_" + os.environ["PADDLE_TRAINER_ID"]), "w").write("x")
            sys.exit(0)
        """)
        sup = _cohort(script)
        assert sup.run() == 0
        # a lone respawn can't rejoin a wedged world: the default for a
        # multi-rank cohort is whole-cohort re-formation, not PR 1's
        # per-rank restart
        assert sup.generation == 1
        assert (tmp_path / "gen1_0").exists()
        assert (tmp_path / "gen1_1").exists()

    def test_spare_host_substitutes_for_the_dead_one(self, tmp_path, capsys):
        script = _write(tmp_path, "child.py", f"""
            import os, sys, time
            if os.environ["{COHORT_GEN_VAR}"] == "0":
                sys.exit(9) if os.environ["PADDLE_TRAINER_ID"] == "1" \\
                    else time.sleep(60)
            ep = os.environ["PADDLE_CURRENT_ENDPOINT"]
            open(os.path.join({str(tmp_path)!r},
                 "ep_" + os.environ["PADDLE_TRAINER_ID"]), "w").write(ep)
            sys.exit(0)
        """)
        sup = _cohort(script, spare_endpoints=["127.0.0.1:7190"])
        assert sup.run() == 0
        assert sup.world == ["127.0.0.1:7101", "127.0.0.1:7190"]
        assert (tmp_path / "ep_1").read_text() == "127.0.0.1:7190"
        assert sup.spares == []  # consumed
        assert "replacing lost" in capsys.readouterr().err

    def test_shrink_on_loss_recomputes_world(self, tmp_path, capsys):
        script = _write(tmp_path, "child.py", f"""
            import os, sys, time
            if os.environ["{COHORT_GEN_VAR}"] == "0":
                sys.exit(9) if os.environ["PADDLE_TRAINER_ID"] == "1" \\
                    else time.sleep(60)
            open(os.path.join({str(tmp_path)!r}, "shrunk"), "w").write(
                os.environ["PADDLE_TRAINERS_NUM"] + ":" +
                os.environ["PADDLE_TRAINER_ENDPOINTS"])
            sys.exit(0)
        """)
        sup = _cohort(script, shrink_on_loss=True)
        assert sup.run() == 0
        assert sup.world == ["127.0.0.1:7101"]
        # the respawned trainer sees the SMALLER world through the normal
        # PADDLE_* contract — dp degree is whatever it recomputes from it
        assert (tmp_path / "shrunk").read_text() == "1:127.0.0.1:7101"
        assert "shrink-to-fit" in capsys.readouterr().err

    def test_heartbeat_declared_death_triggers_reform(self, tmp_path):
        script = _write(tmp_path, "child.py", f"""
            import os, sys, time
            if os.environ["{COHORT_GEN_VAR}"] == "0":
                time.sleep(60)  # alive but silent: the health plane decides
            open(os.path.join({str(tmp_path)!r},
                 "hb_" + os.environ["PADDLE_TRAINER_ID"]), "w").write("x")
            sys.exit(0)
        """)
        sup = _cohort(script)
        # queue the verdict the coordinator thread would deliver; the run
        # loop must tear down BOTH sleeping ranks and re-form
        sup._note_death(1, {"rank": 1, "gen": 0, "step": 4,
                            "host": "h1", "pid": 0, "silent_s": 0.2})
        assert sup.run() == 0
        assert sup.generation == 1
        assert (tmp_path / "hb_0").exists() and (tmp_path / "hb_1").exists()

    def test_preemption_cascade_is_free(self, tmp_path):
        script = _write(tmp_path, "child.py", f"""
            import os, sys
            if os.environ["{COHORT_GEN_VAR}"] == "0":
                sys.exit({PREEMPTION_EXIT_CODE})
            sys.exit(0)
        """)
        sup = _cohort(script, max_restarts=0)  # only a free reform can pass
        assert sup.run() == 0
        assert sup.generation == 1
        assert sup.restarts_used == 0

    def test_budget_exhaustion_propagates_exit_code(self, tmp_path, capsys):
        script = _write(tmp_path, "child.py", """
            import sys
            sys.exit(9)
        """)
        sup = _cohort(script, max_restarts=1)
        assert sup.run() == 9
        assert sup.restarts_used == 1
        assert "budget (1) exhausted" in capsys.readouterr().err

    def test_divergence_is_never_reformed(self, tmp_path):
        script = _write(tmp_path, "child.py", f"""
            import os, sys, time
            sys.exit({DIVERGENCE_EXIT_CODE}) \\
                if os.environ["PADDLE_TRAINER_ID"] == "0" \\
                else time.sleep(60)
        """)
        sup = _cohort(script)
        assert sup.run() == DIVERGENCE_EXIT_CODE
        assert sup.generation == 0 and sup.reforms == 0


class TestInitRetryDedupe:
    """Satellite: ONE initialize-retry implementation (env.py) serves both
    the pre-backend import hook and init_parallel_env."""

    def test_retry_logs_attempts_and_honors_timeout(self, monkeypatch,
                                                    caplog):
        import logging

        import jax

        from paddle_tpu.distributed import env as env_mod
        calls = []

        def refuse(**kw):
            calls.append(kw)
            raise RuntimeError("coordinator not up")

        monkeypatch.setattr(jax.distributed, "initialize", refuse)
        monkeypatch.setenv("PADDLE_TPU_INIT_TIMEOUT", "0.25")
        with caplog.at_level(logging.WARNING,
                             logger="paddle_tpu.distributed.env"):
            with pytest.raises(RuntimeError,
                               match=r"PADDLE_TPU_INIT_TIMEOUT=0\.25"):
                env_mod._initialize_distributed_with_retry(
                    "127.0.0.1:12999", 2, 0)
        assert len(calls) >= 2  # it retried instead of failing fast
        assert calls[0]["coordinator_address"] == "127.0.0.1:12999"
        retry_lines = [r for r in caplog.records
                       if "retrying" in r.getMessage()]
        assert retry_lines
        assert "127.0.0.1:12999" in retry_lines[0].getMessage()

    def test_bootstrap_pre_backend_is_solo_noop(self, monkeypatch):
        from paddle_tpu.distributed import env as env_mod
        monkeypatch.delenv("PADDLE_TRAINERS_NUM", raising=False)
        monkeypatch.delenv("_PADDLE_TPU_DIST_INITIALIZED", raising=False)
        env_mod.bootstrap_pre_backend()  # must not touch jax.distributed
        assert "_PADDLE_TPU_DIST_INITIALIZED" not in os.environ


class TestFlightHeaderIdentity:
    """Satellite: flight dumps carry process identity + cohort generation
    (schema paddle-tpu-flight/2) so post-mortems from a dead cohort are
    attributable without guessing."""

    def test_header_fields(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv(COHORT_GEN_VAR, "3")
        import json
        path = flight.dump("unit_header_probe", directory=str(tmp_path))
        header = json.loads(open(path).read().splitlines()[0])
        assert header["schema"] == "paddle-tpu-flight/2"
        assert header["process_index"] == 1
        assert header["process_count"] == 2
        assert header["cohort_generation"] == 3
