"""Tier-1 tests for the SPMD collective-correctness tier (PTA011 source
lint + PTA012 collective-schedule audit) and the driver satellites that
shipped with it (--changed-only, exit-2 SARIF salvage, docs↔rules
consistency, the collective_bytes audit gate).

Layers:

- seeded-fixture acceptance: every PTA011 finding class fires on
  ``tests/fixtures/spmd_seeded.py`` and each is killable by noqa and by
  a baseline entry;
- pure collective-schedule passes against tiny shard_map programs
  (broken ring, healthy ring, scan trip counts, divergent cond,
  mismatched all_to_all pair, the no-collective negative space);
- PTA012 rule behaviour over synthetic reports (the test seam the
  PTA009/PTA010 tests use);
- the acceptance negatives: PTA011 over the real repo is clean, and the
  check_audit_regression gate fails on seeded collective_bytes
  inflation but tolerates drift within slack.
"""
import dataclasses
import json
import os
import re
import subprocess
import sys

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax                                              # noqa: E402
import jax.numpy as jnp                                 # noqa: E402
import numpy as np                                      # noqa: E402
from jax import lax                                     # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P       # noqa: E402

from paddle_tpu.core.audit import AuditSpec             # noqa: E402
from tools.analyze import trace as trace_mod            # noqa: E402
from tools.analyze.trace import (EntrypointStats,       # noqa: E402
                                 TraceReport, audit_spec, passes)
from tools.analyze.core import (Project, filter_noqa,   # noqa: E402
                                baseline_payload, run_rules,
                                split_findings)
from tools.analyze.rules import rules_by_code           # noqa: E402

PTA011 = rules_by_code()["PTA011"]
PTA012 = rules_by_code()["PTA012"]

FIXTURE = os.path.join("tests", "fixtures", "spmd_seeded.py")


def _driver(args):
    return subprocess.run([sys.executable, "-m", "tools.analyze"] + args,
                          cwd=REPO, capture_output=True, text=True)


def _mesh(n, axis):
    return Mesh(np.array(jax.devices()[:n]), (axis,))


# -- PTA011 seeded-fixture acceptance ----------------------------------------

def test_spmd_fixture_fires_every_pta011_class_and_nothing_else():
    proc = _driver(["--baseline", "none", "--rule", "PTA011", "--json",
                    FIXTURE])
    assert proc.returncode == 1, proc.stdout
    found = json.loads(proc.stdout)["findings"]
    assert all(f["rule"] == "PTA011" for f in found)
    assert all(f["severity"] == "error" for f in found)
    blob = " | ".join(f["message"] for f in found)
    # (a) rank-gated: one via the direct lax call, one via the
    # env-derived rank variable gating a collective wrapper
    assert blob.count("reachable only under rank-dependent") == 2
    assert "`jax.process_index()`" in blob
    assert "env `PADDLE_TRAINER_ID`" in blob
    # (b) swallowed collective
    assert "whose `except Exception`" in blob
    assert "re-raise so the whole cohort fails together" in blob
    # (c) axis hygiene: 'pd' is the seeded typo; the ring fixture's 'r'
    # axis is declared by make_ring_mesh and must NOT fire
    assert "names axis 'pd'" in blob
    assert "names axis 'r'" not in blob
    # (d) per-host loop trip count
    assert "loop whose trip count derives from a per-host value" in blob
    assert len(found) == 5, [f["message"] for f in found]
    # the clean_* functions stay clean: uniform psum with jnp.where
    # masking and a rank-gated print are both sanctioned idioms
    lines = {f["line"] for f in found}
    src = open(os.path.join(REPO, FIXTURE)).read().splitlines()
    for i, text in enumerate(src, 1):
        if "clean_" in text and "def " in text:
            assert not any(i <= ln <= i + 5 for ln in lines)


def test_pta011_killable_by_noqa(tmp_path):
    src = open(os.path.join(REPO, FIXTURE)).read()
    patched = []
    for line in src.splitlines():
        if ("lax.psum" in line or "all_reduce(x)" in line
                or "lax.all_gather" in line or "lax.ppermute" in line):
            line += "  # noqa: PTA011 -- seeded fixture, deliberately divergent"
        patched.append(line)
    p = tmp_path / "spmd_noqa.py"
    p.write_text("\n".join(patched) + "\n")
    proc = _driver(["--baseline", "none", "--rule", "PTA011", "--json",
                    str(p)])
    assert proc.returncode == 0, proc.stdout
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["counts"]["suppressed"] == 5


def test_pta011_killable_by_baseline(tmp_path):
    bl = tmp_path / "baseline.json"
    wrote = _driver(["--baseline", str(bl), "--write-baseline",
                     "--rule", "PTA011", FIXTURE])
    assert wrote.returncode == 0, wrote.stdout
    proc = _driver(["--baseline", str(bl), "--rule", "PTA011", "--json",
                    FIXTURE])
    assert proc.returncode == 0, proc.stdout
    payload = json.loads(proc.stdout)
    assert payload["counts"]["new"] == 0
    assert payload["counts"]["baselined"] == 5


def test_pta011_clean_on_fleet_code():
    # the real fleet code uses the uniform-schedule idioms (jnp.where
    # masking, lax.switch) — the rule must not invent findings there.
    # (test_analyze_perf covers the full repo with the default tier.)
    proc = _driver(["--baseline", "none", "--rule", "PTA011", "--json",
                    "paddle_tpu/distributed"])
    assert proc.returncode == 0, proc.stdout
    assert json.loads(proc.stdout)["findings"] == []


# -- collective-schedule pass (jaxpr level) -----------------------------------

def _schedule_of(fn, *args, n=4, axis="r", in_specs=P("r"),
                 out_specs=P("r")):
    from jax.experimental.shard_map import shard_map
    wrapped = shard_map(fn, mesh=_mesh(n, axis), in_specs=in_specs,
                        out_specs=out_specs, check_rep=False)
    return passes.collective_schedule(jax.make_jaxpr(wrapped)(*args))


def test_broken_ring_permutation_flagged():
    from tests.fixtures.spmd_seeded import broken_ring_body
    sched, issues = _schedule_of(broken_ring_body, jnp.zeros((8, 4)))
    assert [e["primitive"] for e in sched] == ["ppermute"]
    assert sched[0]["perm_kind"] == "partial"
    assert len(issues) == 1 and issues[0]["kind"] == "broken-permutation"
    assert issues[0]["axis_size"] == 4
    assert issues[0]["covered_ranks"] == [0, 1, 2]   # rank 3 orphaned


def test_healthy_ring_and_open_chain_pass():
    def ring(x):
        return lax.ppermute(x, "r", perm=[(i, (i + 1) % 4)
                                          for i in range(4)])

    def chain(x):  # the pipeline's open shift: covers every rank
        return lax.ppermute(x, "r", perm=[(i, i + 1) for i in range(3)])

    for fn, kind in ((ring, "ring"), (chain, "shift")):
        sched, issues = _schedule_of(fn, jnp.zeros((8, 4)))
        assert issues == []
        assert sched[0]["perm_kind"] == kind


def test_classify_perm_edge_cases():
    cp = passes._classify_perm
    assert cp([(0, 1), (1, 0)], 2) == "ring"
    assert cp([(0, 1), (0, 2)], 4) == "invalid"      # duplicate source
    assert cp([(0, 5)], 4) == "invalid"              # out of range
    assert cp([(0, 1), (1, 0), (2, 3), (3, 2)], 4) == "multi-cycle"
    assert cp([(0, 1)], None) == "unknown"
    assert cp([], 4) == "empty"


def test_scan_multiplies_trip_count_into_wire_bytes():
    def body(x):
        def step(c, _):
            return lax.psum(c, "r"), None
        out, _ = lax.scan(step, x, None, length=5)
        return out

    sched, issues = _schedule_of(body, jnp.zeros((8, 4), jnp.float32))
    assert issues == []
    (entry,) = sched
    assert entry["primitive"] == "psum"
    assert entry["trip_count"] == 5
    assert entry["bytes"] == 5 * 2 * 4 * 4   # trips × local [2,4] f32


def test_rank_divergent_cond_branches_flagged():
    def body(x):
        return lax.cond(jnp.sum(x) > 0,
                        lambda v: lax.psum(v, "r"),
                        lambda v: v * 2.0, x)

    sched, issues = _schedule_of(body, jnp.zeros((8, 4), jnp.float32))
    assert any(i["kind"] == "rank-divergent-cond" for i in issues)


def test_uniform_cond_branches_pass():
    def body(x):
        return lax.cond(jnp.sum(x) > 0,
                        lambda v: lax.psum(v, "r"),
                        lambda v: lax.psum(v * 2.0, "r"), x)

    _, issues = _schedule_of(body, jnp.zeros((8, 4), jnp.float32))
    assert issues == []


def test_mismatched_all_to_all_pair_flagged():
    def body(x):
        y = lax.all_to_all(x, "r", 0, 1, tiled=True)
        return lax.all_to_all(y, "r", 0, 1, tiled=True)  # must be 1,0

    _, issues = _schedule_of(body, jnp.zeros((64, 8), jnp.float32))
    assert any(i["kind"] == "alltoall-pairing" for i in issues)

    def ok(x):   # dispatch then the transposed return trip
        y = lax.all_to_all(x, "r", 0, 1, tiled=True)
        return lax.all_to_all(y, "r", 1, 0, tiled=True)

    _, issues = _schedule_of(ok, jnp.zeros((64, 8), jnp.float32))
    assert issues == []


def test_no_collective_entrypoint_negative_space():
    # single-device entrypoints must yield an empty schedule and zero
    # issues — no rank-invariance false positive on collective-free code
    def step(x):
        return jnp.tanh(x) * 2.0 + 1.0

    spec = AuditSpec(fn=step,
                     make_args=lambda v: (jnp.full((4, 4), float(v)),))
    st = audit_spec("no_collectives", spec)
    assert st.error == ""
    assert st.collectives == []
    assert st.collective_bytes == 0
    assert st.collective_issues == []


# -- PTA012 rule over reports -------------------------------------------------

def _report_with(**overrides):
    st = EntrypointStats(name="ep", tags=("train",),
                         path=FIXTURE, line=76)
    for k, v in overrides.items():
        setattr(st, k, v)
    return TraceReport(platform="cpu", entrypoint_stats={"ep": st})


def _pta012_findings(report, monkeypatch):
    monkeypatch.setattr(trace_mod, "_LAST", report)
    return PTA012.finalize(None)


def test_pta012_flags_broken_permutation_as_error(monkeypatch):
    fs = _pta012_findings(_report_with(collective_issues=[{
        "kind": "broken-permutation", "axis": "r", "axis_size": 4,
        "perm": [[0, 1], [1, 2], [2, 0]], "classification": "partial",
        "covered_ranks": [0, 1, 2]}]), monkeypatch)
    assert len(fs) == 1
    assert fs[0].severity == "error"
    assert "partial permutation" in fs[0].message
    assert fs[0].anchor == "trace:ep:broken-perm:r"
    assert (fs[0].path, fs[0].line) == (FIXTURE, 76)


def test_pta012_flags_divergent_cond_and_pairing(monkeypatch):
    fs = _pta012_findings(_report_with(collective_issues=[
        {"kind": "rank-divergent-cond",
         "branch_schedules": [["psum"], []]},
        {"kind": "alltoall-pairing", "axis": "ep",
         "first": [0, 1], "second": [0, 1]}]), monkeypatch)
    sev = {f.anchor: f.severity for f in fs}
    assert sev["trace:ep:rank-divergent-cond"] == "error"
    assert sev["trace:ep:alltoall-pairing:ep"] == "warning"


def test_pta012_quiet_on_clean_stats_and_broken_entrypoints(monkeypatch):
    assert _pta012_findings(_report_with(), monkeypatch) == []
    # a build failure is PTA009's finding; PTA012 must not double-report
    assert _pta012_findings(_report_with(error="boom"), monkeypatch) == []


def test_pta012_killable_by_baseline(monkeypatch):
    fs = _pta012_findings(_report_with(collective_issues=[{
        "kind": "broken-permutation", "axis": "r", "axis_size": 4,
        "perm": [[0, 1]], "classification": "partial",
        "covered_ranks": [0, 1]}]), monkeypatch)
    baseline = baseline_payload(fs)["findings"]
    new, baselined, expired = split_findings(fs, baseline)
    assert new == [] and len(baselined) == 1 and expired == []


def test_pta012_killable_by_noqa(tmp_path, monkeypatch):
    # trace findings anchor at the registration site: a noqa on that
    # line suppresses them like any AST finding
    reg = tmp_path / "reg.py"
    reg.write_text("register_entrypoint('ep', f)"
                   "  # noqa: PTA012 -- seeded broken ring, negative test\n")
    fs = _pta012_findings(_report_with(collective_issues=[{
        "kind": "broken-permutation", "axis": "r", "axis_size": 4,
        "perm": [[0, 1]], "classification": "partial",
        "covered_ranks": [0, 1]}]), monkeypatch)
    fs = [dataclasses.replace(f, path="reg.py", line=1) for f in fs]
    project = Project(str(tmp_path), ["reg.py"])
    kept, suppressed = filter_noqa(project, fs)
    assert kept == [] and len(suppressed) == 1


def test_pta012_end_to_end_on_seeded_broken_ring():
    from jax.experimental.shard_map import shard_map
    from tests.fixtures.spmd_seeded import broken_ring_body
    fn = shard_map(broken_ring_body, mesh=_mesh(4, "r"),
                   in_specs=P("r"), out_specs=P("r"), check_rep=False)
    spec = AuditSpec(fn=fn, make_args=lambda v: (
        jnp.full((8, 4), float(v), jnp.float32),))
    st = audit_spec("seeded_ring", spec)
    assert st.error == ""
    assert [i["kind"] for i in st.collective_issues] == \
        ["broken-permutation"]
    assert st.collective_bytes > 0


# -- collective_bytes audit gate ----------------------------------------------

def _gate():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_audit_regression as gate
    return gate


def test_collective_bytes_regression_fails_gate():
    gate = _gate()
    name = gate.ENTRYPOINTS[0]
    counters = {"host_transfers": 0, "large_consts": 0,
                "donatable_inputs": 0, "retraces": 0,
                "fingerprint_unstable": 0, "copy_fraction": 0.0,
                "collective_bytes": 1000, "collective_issues": 0}
    base = {name: dict(counters)}
    ok = {name: dict(counters, collective_bytes=1040)}     # within 5%
    bad = {name: dict(counters, collective_bytes=1100)}    # beyond
    assert not any("collective_bytes" in p
                   for p in gate.compare(base, ok))
    problems = gate.compare(base, bad)
    assert any("collective_bytes regressed 1000 -> 1100" in p
               for p in problems)
    # a new schedule-invariant violation is a regression even when the
    # byte count stays flat
    worse = {name: dict(counters, collective_issues=1)}
    assert any("collective_issues" in p
               for p in gate.compare(base, worse))


def test_gate_summarize_reads_collective_fields():
    gate = _gate()
    payload = {"entrypoints": {
        gate.ENTRYPOINTS[0]: {
            "transfers": [], "large_consts": [], "donation": None,
            "trace_count": 1, "fingerprint_stable": True,
            "hlo": {"instructions": 10, "copies": 0},
            "collectives": [{"primitive": "psum", "bytes": 256}],
            "collective_bytes": 256, "collective_issues": []}}}
    cur = gate.summarize(payload)[gate.ENTRYPOINTS[0]]
    assert cur["collective_bytes"] == 256
    assert cur["collective_issues"] == 0


def test_committed_baseline_has_collective_bytes_for_mesh_entrypoints():
    with open(os.path.join(REPO, "bench_audit_baseline.json")) as f:
        entries = json.load(f)["entrypoints"]
    gate = _gate()
    assert set(gate.ENTRYPOINTS) == set(entries)
    for name in ("pipeline_train_step", "moe_train_step",
                 "compressed_allreduce_train_step",
                 "gpt_ring_flash_train_step"):
        assert entries[name]["collective_bytes"] > 0, name


# -- satellites ---------------------------------------------------------------

def test_docs_rules_table_matches_list_rules():
    proc = _driver(["--list-rules"])
    assert proc.returncode == 0
    listed = set(re.findall(r"^(PTA\d{3})", proc.stdout, re.M))
    docs = open(os.path.join(REPO, "docs", "static_analysis.md")).read()
    documented = set(re.findall(r"^\| (PTA\d{3}) \|", docs, re.M))
    # PTA000 (syntax error) is synthesized by the core, not a registered
    # rule — it is documented but never listed
    assert documented - {"PTA000"} == listed
    assert "PTA000" in documented


def test_changed_only_scopes_to_diffed_files(tmp_path):
    def git(*argv):
        subprocess.run(["git", "-c", "user.email=t@t", "-c",
                        "user.name=t"] + list(argv), cwd=tmp_path,
                       check=True, capture_output=True)

    (tmp_path / "a.py").write_text("x = 1\n")
    (tmp_path / "skipme.py").write_text("def broken(:\n")
    git("init", "-q")
    git("add", "a.py", "skipme.py")
    git("commit", "-qm", "seed")

    # no changes: clean exit, nothing analyzed
    proc = _driver(["--root", str(tmp_path), "--changed-only",
                    "--baseline", "none", "."])
    assert proc.returncode == 0, proc.stdout
    assert "no changed .py files" in proc.stdout

    # one modified + one untracked file: both analyzed, the committed
    # (unchanged) broken file is NOT — proof of scoping
    (tmp_path / "a.py").write_text("def broken(:\n")
    (tmp_path / "b.py").write_text("def broken(:\n")
    proc = _driver(["--root", str(tmp_path), "--changed-only",
                    "--baseline", "none", "--json", "."])
    assert proc.returncode == 1, proc.stdout
    found = json.loads(proc.stdout)["findings"]
    assert sorted(f["path"] for f in found) == ["a.py", "b.py"]
    assert all(f["rule"] == "PTA000" for f in found)


def test_exit_2_overwrites_stale_sarif_with_valid_notification(
        tmp_path, monkeypatch):
    import tools.analyze.__main__ as main_mod
    out = tmp_path / "analysis.sarif"
    out.write_text("STALE NOT JSON")

    def boom(*a, **k):
        raise RuntimeError("seeded internal failure")

    monkeypatch.setattr(main_mod, "run_rules", boom)
    rc = main_mod.main(["--format", "sarif", "--output", str(out),
                        "--baseline", "none", FIXTURE])
    assert rc == 2
    doc = json.loads(out.read_text())   # valid JSON, not the stale blob
    run = doc["runs"][0]
    inv = run["invocations"][0]
    assert inv["executionSuccessful"] is False
    notes = inv["toolExecutionNotifications"]
    assert "seeded internal failure" in notes[0]["message"]["text"]
    assert run["results"] == []
    assert run["tool"]["driver"]["name"] == "paddle-tpu-analyze"


def test_successful_sarif_marks_execution_successful(tmp_path):
    out = tmp_path / "ok.sarif"
    proc = _driver(["--baseline", "none", "--rule", "PTA011",
                    "--format", "sarif", "--output", str(out), FIXTURE])
    assert proc.returncode == 1   # seeded findings gate
    doc = json.loads(out.read_text())
    assert doc["runs"][0]["invocations"][0]["executionSuccessful"] is True
    assert len(doc["runs"][0]["results"]) == 5
