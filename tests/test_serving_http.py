"""serving.http front-end: /predict, /healthz, /statsz, error mapping,
and the `python -m paddle_tpu.serving` CLI argument plumbing."""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.serving import Engine, EngineConfig
from paddle_tpu.serving.http import make_server


def _double(*arrays):
    return [np.asarray(a) * 2.0 for a in arrays]


@pytest.fixture()
def served():
    eng = Engine(_double, EngineConfig(max_batch=8, max_batch_delay=0.005),
                 registry=StatRegistry())
    srv = make_server(eng, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield eng, srv.server_address[1]
    srv.shutdown()
    srv.server_close()
    eng.drain()


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(port, path, payload):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestHTTP:
    def test_healthz_ok_then_draining(self, served):
        eng, port = served
        assert _get(port, "/healthz") == (200, {"status": "ok"})
        eng.begin_drain()
        code, body = _get(port, "/healthz")
        assert code == 503 and body["status"] == "draining"

    def test_predict_roundtrip(self, served):
        _, port = served
        x = [[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]
        code, body = _post(port, "/predict", {"inputs": [x]})
        assert code == 200
        assert body["shapes"] == [[3, 2]]
        assert np.allclose(body["outputs"][0], np.asarray(x) * 2.0)
        assert body["req_ms"] > 0

    def test_predict_int_dtype(self, served):
        _, port = served
        code, body = _post(port, "/predict", {
            "inputs": [[[1, 2], [3, 4]]], "dtypes": ["int32"]})
        assert code == 200
        assert body["outputs"][0] == [[2, 4], [6, 8]]

    def test_bad_request_400(self, served):
        _, port = served
        code, body = _post(port, "/predict", {"wrong_key": []})
        assert code == 400 and "bad request" in body["error"]

    def test_unknown_route_404(self, served):
        _, port = served
        assert _get(port, "/nope")[0] == 404
        assert _post(port, "/nope", {})[0] == 404

    def test_statsz_counts_requests(self, served):
        _, port = served
        for _ in range(3):
            _post(port, "/predict", {"inputs": [[[1.0, 1.0]]]})
        code, stats = _get(port, "/statsz")
        assert code == 200
        assert stats["stats"]["serving.completed"] == 3
        assert stats["histograms"]["serving.latency_ms"]["count"] == 3
        assert stats["executable_cache"]["misses"] >= 1
        assert stats["draining"] is False

    def test_draining_predict_503(self, served):
        eng, port = served
        eng.begin_drain()
        eng._stopped.wait(10)
        code, body = _post(port, "/predict", {"inputs": [[[1.0, 1.0]]]})
        assert code == 503 and "drain" in body["error"]


class TestCLI:
    def test_parse_and_serve_smoke(self, tmp_path):
        """Drive main() with a real artifact on an ephemeral port, hit
        /healthz, then SIGTERM-equivalent drain via begin_drain."""
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.static import InputSpec

        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(3, 2)

            def forward(self, x):
                return self.fc(x)

        prefix = str(tmp_path / "cli_model")
        paddle.jit.save(Net(), prefix,
                        input_spec=[InputSpec([None, 3], "float32", "x")])

        from paddle_tpu.serving import Engine, EngineConfig
        from paddle_tpu.serving.__main__ import _parse_int_list

        assert _parse_int_list("1,2,8") == [1, 2, 8]
        assert _parse_int_list("") == []

        # engine-from-path-prefix (what the CLI constructs)
        eng = Engine(prefix, EngineConfig(max_batch=4),
                     registry=StatRegistry())
        out, = eng.submit([np.ones((2, 3), np.float32)]).result(60)
        assert out.shape == (2, 2)
        eng.drain()
