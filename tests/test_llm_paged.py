"""Paged KV cache (serving/llm/paged/): page pool + block tables, the
paged decode/prefill/spec programs, COW prefix sharing, page-granular
admission — and the contracts the slot path must keep (double-free
hardening, bitwise decode parity)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig, StaticKVCache
from paddle_tpu.serving.llm.decode import (_AUDIT_SPEC, _audit_params,
                                           build_decode_step,
                                           build_prefill_fn)
from paddle_tpu.serving.llm.paged import (GPTPagedDecoder, PagedKVCache,
                                          PagePool, PagesExhausted,
                                          build_paged_decode_step,
                                          build_paged_prefill_fn,
                                          paged_gather_rows,
                                          pages_for_tokens)
from paddle_tpu.serving.llm.paged.prefix import PagedPrefixStore
from paddle_tpu.ops.paged_attention import paged_attention


def _tiny_model(seed=0, vocab=64, hidden=32, layers=2, heads=4,
                max_pos=128):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=layers, num_heads=heads,
                    max_position_embeddings=max_pos,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


def _engine(model, **kw):
    cfg = dict(num_slots=4, max_seq=64, prefill_buckets=(8, 16, 40),
               warmup=True, seed=3)
    cfg.update(kw)
    return LLMEngine(model, LLMEngineConfig(**cfg),
                     registry=StatRegistry())


class TestPagePool:
    def test_alloc_release_refcount(self):
        pool = PagePool(4)
        a, b = pool.alloc(), pool.alloc()
        assert pool.pages_in_use == 2 and pool.free_pages == 2
        pool.retain(a)
        assert pool.refcount(a) == 2
        assert pool.release(a) is False      # still referenced
        assert pool.release(a) is True       # back on the free list
        assert pool.release(b) is True
        assert pool.pages_in_use == 0

    def test_release_double_free_raises(self):
        pool = PagePool(2)
        p = pool.alloc()
        pool.release(p)
        with pytest.raises(ValueError, match="double-free"):
            pool.release(p)

    def test_retain_free_page_raises(self):
        pool = PagePool(2)
        with pytest.raises(ValueError):
            pool.retain(0)

    def test_alloc_many_atomic(self):
        pool = PagePool(3)
        pool.alloc()
        with pytest.raises(PagesExhausted):
            pool.alloc_many(3)
        # the failed alloc must not have leaked any page
        assert pool.pages_in_use == 1
        assert len(pool.alloc_many(2)) == 2

    def test_lowest_page_first(self):
        pool = PagePool(4)
        a = pool.alloc()
        b = pool.alloc()
        pool.release(a)
        assert pool.alloc() == a             # heap reuses the lowest id
        assert b == 1

    def test_pages_for_tokens(self):
        assert pages_for_tokens(0, 8) == 0
        assert pages_for_tokens(1, 8) == 1
        assert pages_for_tokens(8, 8) == 1
        assert pages_for_tokens(9, 8) == 2


class TestPagedKVCache:
    def _kv(self, **kw):
        cfg = dict(num_slots=2, num_layers=1, max_seq=16, num_heads=2,
                   head_dim=4, page_size=4, num_pages=8)
        cfg.update(kw)
        return PagedKVCache(**cfg)

    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="page_size"):
            self._kv(page_size=5)
        with pytest.raises(ValueError, match="num_pages"):
            self._kv(num_pages=3)            # < pages_per_seq

    def test_block_tables_start_at_trash(self):
        kv = self._kv()
        assert kv.trash == 8
        assert (np.asarray(kv.block_tables) == kv.trash).all()

    def test_slot_lifecycle_and_double_free(self):
        kv = self._kv()
        slot = kv.alloc()
        kv.ensure_pages(slot, 6)             # 2 pages
        assert kv.mapped_pages(slot) == 2
        assert kv.pool.pages_in_use == 2
        kv.free(slot)
        assert kv.pool.pages_in_use == 0
        assert (np.asarray(kv.block_tables[slot]) == kv.trash).all()
        with pytest.raises(ValueError, match="double free"):
            kv.free(slot)

    def test_ensure_pages_atomic_on_exhaustion(self):
        kv = self._kv(num_pages=4)
        s0, s1 = kv.alloc(), kv.alloc()
        kv.ensure_pages(s0, 12)              # 3 of 4 pages
        with pytest.raises(PagesExhausted):
            kv.ensure_pages(s1, 8)           # needs 2, only 1 left
        assert kv.mapped_pages(s1) == 0      # nothing partially mapped
        assert kv.pool.pages_in_use == 3

    def test_adopt_shared_and_copied(self):
        kv = self._kv(num_slots=3, num_pages=12)
        owner = kv.alloc()
        kv.ensure_pages(owner, 4)
        pid = kv.slot_page_ids(owner)[0]
        kv.pool.retain(pid)                  # the store's reference
        other = kv.alloc()
        kv.adopt_shared_page(other, pid)
        assert kv.pool.refcount(pid) == 3
        assert kv.slot_page_ids(other)[0] == pid
        third = kv.alloc()
        new_pid = kv.adopt_copied_page(third, pid)
        assert new_pid != pid and kv.cow_splits == 1
        assert kv.pool.refcount(pid) == 3    # copy took no reference
        # the copy is bitwise-identical arena content
        assert (np.asarray(kv.k[new_pid]) == np.asarray(kv.k[pid])).all()
        for s in (owner, other, third):
            kv.free(s)
        kv.pool.release(pid)
        assert kv.pool.pages_in_use == 0


class TestStaticKVCacheDoubleFree:
    """Satellite regression: free() must reject a stale slot id instead
    of corrupting the free list (a double-freed slot handed to two
    sequences interleaves their KV rows)."""

    def test_double_free_raises(self):
        kv = StaticKVCache(num_slots=2, num_layers=1, max_seq=8,
                           num_heads=2, head_dim=4)
        slot = kv.alloc()
        kv.free(slot)
        with pytest.raises(ValueError, match="double free"):
            kv.free(slot)

    def test_out_of_range_raises(self):
        kv = StaticKVCache(num_slots=2, num_layers=1, max_seq=8,
                           num_heads=2, head_dim=4)
        with pytest.raises(ValueError):
            kv.free(7)
        with pytest.raises(ValueError):
            kv.free(-1)


class TestStepParity:
    """Slot-vs-paged bitwise parity of the raw decode programs: same
    shapes, same reduction order, so greedy AND seeded top-k sampling
    must produce identical tokens (the paged gather lane's contract)."""

    def _run(self, mode):
        spec = _AUDIT_SPEC
        rng = np.random.default_rng(0)
        params = _audit_params(rng)
        S, max_seq, page = 2, 16, 4
        L = spec.num_layers
        H, D = spec.num_heads, spec.head_dim
        slot_step = build_decode_step(spec, 4)
        paged_step = build_paged_decode_step(spec, 4, page, "gather")
        slot_pre = build_prefill_fn(spec, 4)
        paged_pre = build_paged_prefill_fn(spec, 4, page)
        kb_s = jnp.zeros((S, L, max_seq, H, D), jnp.float32)
        vb_s = jnp.zeros_like(kb_s)
        kb_p = jnp.zeros((9, L, page, H, D), jnp.float32)
        vb_p = jnp.zeros_like(kb_p)
        bt = jnp.asarray([[0, 1, 2, 3], [4, 5, 6, 7]], jnp.int32)
        lengths = jnp.zeros((S,), jnp.int32)
        finished = jnp.zeros((S,), bool)
        tokens = jnp.asarray(rng.integers(0, spec.vocab_size, (S, 8)),
                             jnp.int32)
        true_lens = jnp.asarray([5, 3], jnp.int32)
        slot_ids = jnp.asarray([0, 1], jnp.int32)
        temp, topk, dos = ((1.0, 0, False) if mode == "greedy"
                           else (0.9, 3, True))
        samp = (jnp.full((S,), temp, jnp.float32),
                jnp.full((S,), topk, jnp.int32),
                jnp.full((S,), dos, bool),
                jnp.full((S,), -1, jnp.int32))
        key = jax.random.PRNGKey(7)
        ks, vs, ls, fs, last_s = jax.jit(slot_pre)(
            params, tokens, true_lens, kb_s, vb_s, lengths, finished,
            slot_ids, *samp, key)
        kp, vp, lp, fp, last_p = jax.jit(paged_pre)(
            params, tokens, true_lens, kb_p, vb_p, bt, lengths, finished,
            slot_ids, *samp, key)
        assert (np.asarray(last_s) == np.asarray(last_p)).all()
        for i in range(6):
            key = jax.random.PRNGKey(100 + i)
            ks, vs, ls, fs, last_s = jax.jit(slot_step)(  # noqa: PTA008 -- same fn object each pass: pjit cache hit, parity test wants the jitted lane
                params, ks, vs, ls, fs, last_s, *samp, key)
            kp, vp, lp, fp, last_p = jax.jit(paged_step)(  # noqa: PTA008 -- same fn object each pass: pjit cache hit, parity test wants the jitted lane
                params, kp, vp, bt, lp, fp, last_p, *samp, key)
            assert (np.asarray(last_s) == np.asarray(last_p)).all(), \
                (mode, i)
            assert (np.asarray(ls) == np.asarray(lp)).all()
        # the gathered valid rows are the slot rows, bitwise
        g = paged_gather_rows(kp[:, 0], bt)
        sl = ks[:, 0]
        for si, ln in enumerate(np.asarray(ls)):
            assert (np.asarray(g[si, :ln])
                    == np.asarray(sl[si, :ln])).all()

    def test_greedy_bitwise(self):
        self._run("greedy")

    def test_seeded_topk_bitwise(self):
        self._run("topk")


class TestPagedAttentionKernel:
    def test_matches_gather_reference(self):
        rng = np.random.default_rng(3)
        S, H, D, page, pp = 3, 4, 8, 4, 3
        num_pages = S * pp
        q = jnp.asarray(rng.standard_normal((S, H, D)), jnp.float32)
        ka = jnp.asarray(rng.standard_normal(
            (num_pages + 1, page, H, D)), jnp.float32)
        va = jnp.asarray(rng.standard_normal(ka.shape), jnp.float32)
        bt = jnp.arange(num_pages, dtype=jnp.int32).reshape(S, pp)
        positions = jnp.asarray([2, 7, 11], jnp.int32)
        out = paged_attention(q, ka, va, bt, positions, interpret=True)
        # reference: gather the pages dense, mask, softmax
        kg = paged_gather_rows(ka, bt)           # [S, pp*page, H, D]
        vg = paged_gather_rows(va, bt)
        scale = 1.0 / np.sqrt(D)
        mask = (jnp.arange(pp * page)[None, :]
                <= positions[:, None])           # [S, T]
        logits = jnp.einsum("shd,sthd->sht", q * scale, kg)
        logits = jnp.where(mask[:, None, :], logits, -1e30)
        w = jax.nn.softmax(logits, axis=-1)
        ref = jnp.einsum("sht,sthd->shd", w, vg)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_int8_arena(self):
        q = jnp.zeros((1, 2, 4), jnp.float32)
        arena = {"q": jnp.zeros((3, 4, 2, 4), jnp.int8),
                 "s": jnp.zeros((3, 4), jnp.float32)}
        bt = jnp.zeros((1, 2), jnp.int32)
        pos = jnp.zeros((1,), jnp.int32)
        with pytest.raises(ValueError, match="dense"):
            paged_attention(q, arena, arena, bt, pos)


class TestEngineParity:
    """End-to-end greedy decode through the engine: the paged layout
    must be invisible in the tokens."""

    PROMPTS = [(5,), (11,), (20,), (33,)]

    def _prompts(self, vocab=64):
        rng = np.random.default_rng(5)
        return [list(rng.integers(0, vocab, n)) for (n,) in self.PROMPTS]

    def test_greedy_bitwise_and_leak_free(self, model):
        prompts = self._prompts()
        slot_eng = _engine(model)
        slot_out = [slot_eng.generate(p, max_new_tokens=6)["tokens"]
                    for p in prompts]
        slot_eng.drain(timeout=120)
        paged_eng = _engine(model, kv_layout="paged", page_size=8)
        paged_out = [paged_eng.generate(p, max_new_tokens=6)["tokens"]
                     for p in prompts]
        st = paged_eng.stats()
        assert slot_out == paged_out
        assert st["kv_layout"] == "paged"
        assert st["pages"]["total"] == 4 * 64 // 8
        kv = paged_eng._batcher.kv
        paged_eng.drain(timeout=120)
        assert kv.pool.pages_in_use == 0     # every exit path released
        assert kv.pool.total_allocs == kv.pool.total_releases

    def test_spec_decode_composed_parity(self, model):
        draft = _tiny_model(seed=1, layers=1)
        prompts = self._prompts()[:3]
        plain = _engine(model)
        plain_out = [plain.generate(p, max_new_tokens=6)["tokens"]
                     for p in prompts]
        plain.drain(timeout=120)
        paged = LLMEngine(model, LLMEngineConfig(
            num_slots=4, max_seq=64, prefill_buckets=(8, 16, 40),
            warmup=True, seed=3, spec_k=2, kv_layout="paged",
            page_size=8), registry=StatRegistry(), draft_model=draft)
        paged_out = [paged.generate(p, max_new_tokens=6)["tokens"]
                     for p in prompts]
        kv = paged._batcher.kv
        paged.drain(timeout=120)
        assert plain_out == paged_out        # spec decode is lossless
        assert kv.pool.pages_in_use == 0

    @pytest.mark.slow      # ~10s of int8 executable compiles; the fast
    # int8 contract (dict-arena kernel rejection + step-level parity)
    # stays in tier-1 via TestPagedAttentionKernel/TestStepParity
    def test_int8_page_parity(self, model):
        prompts = self._prompts()[:3]
        slot8 = _engine(model, kv_dtype="int8")
        slot_out = [slot8.generate(p, max_new_tokens=6)["tokens"]
                    for p in prompts]
        slot8.drain(timeout=120)
        paged8 = _engine(model, kv_dtype="int8", kv_layout="paged",
                         page_size=8)
        paged_out = [paged8.generate(p, max_new_tokens=6)["tokens"]
                     for p in prompts]
        kv = paged8._batcher.kv
        assert kv.quantized
        paged8.drain(timeout=120)
        assert slot_out == paged_out
        assert kv.pool.pages_in_use == 0


class TestPrefixSharing:
    def test_aligned_hit_is_zero_copy(self, model):
        rng = np.random.default_rng(11)
        sysp = list(rng.integers(0, 64, 24))     # 3 pages, page_size 8
        eng = _engine(model, kv_layout="paged", page_size=8,
                      prefix_cache=True)
        r1 = eng.generate(sysp + [1, 2, 3], max_new_tokens=4)["tokens"]
        r2 = eng.generate(sysp + [1, 2, 3], max_new_tokens=4)["tokens"]
        r3 = eng.generate(sysp + [9, 9], max_new_tokens=4)["tokens"]
        ps = eng.prefix_store.stats()
        assert r1 == r2
        assert ps["hits"] == 2 and ps["misses"] == 1
        # 27-token prompts align to a 24-token (3-page) head: every hit
        # splices those pages by refcount — zero bytes copied
        page_nbytes = eng._batcher.kv.page_nbytes()
        assert ps["bytes_copied"] == 0
        assert ps["bytes_shared"] == 2 * 3 * page_nbytes
        reg = eng.registry
        assert reg.get("serving.llm.pages_cow_splits") == 0
        assert reg.get("serving.llm.pages_free") > 0
        # correctness of the divergent third request vs an unshared run
        ref = _engine(model, kv_layout="paged", page_size=8)
        assert r1 == ref.generate(sysp + [1, 2, 3],
                                  max_new_tokens=4)["tokens"]
        assert r3 == ref.generate(sysp + [9, 9],
                                  max_new_tokens=4)["tokens"]
        ref.drain(timeout=120)
        kv = eng._batcher.kv
        eng.drain(timeout=120)
        eng.prefix_store.clear()
        assert kv.pool.pages_in_use == 0

    def test_cow_split_on_partial_page_divergence(self, model):
        rng = np.random.default_rng(13)
        p1 = list(rng.integers(0, 64, 32))       # 4 pages, aligned
        d = (p1[30] + 1) % 64
        p2 = p1[:30] + [d]                       # diverges inside page 3
        eng = _engine(model, kv_layout="paged", page_size=8,
                      prefix_cache=True)
        r1 = eng.generate(p1, max_new_tokens=4)["tokens"]
        r2 = eng.generate(p2, max_new_tokens=4)["tokens"]
        r1b = eng.generate(p1, max_new_tokens=4)["tokens"]
        kv = eng._batcher.kv
        ps = eng.prefix_store.stats()
        # p2 shares 3 full pages, then COWs the partial 4th: rows 24..29
        # reuse the copy, row 30 (the divergent token) writes into it
        assert kv.cow_splits >= 1
        assert ps["bytes_copied"] >= kv.page_nbytes()
        assert eng.registry.get("serving.llm.pages_cow_splits") >= 1
        # shared pages stayed immutable: both sequences decode exactly
        # like unshared engines
        ref = _engine(model, kv_layout="paged", page_size=8)
        assert r1 == ref.generate(p1, max_new_tokens=4)["tokens"]
        assert r2 == ref.generate(p2, max_new_tokens=4)["tokens"]
        assert r1b == r1
        ref.drain(timeout=120)
        eng.drain(timeout=120)
        eng.prefix_store.clear()
        assert kv.pool.pages_in_use == 0

    def test_store_evict_unpinned_releases_pages(self):
        kv = PagedKVCache(num_slots=2, num_layers=1, max_seq=16,
                          num_heads=2, head_dim=4, page_size=4,
                          num_pages=8)
        store = PagedPrefixStore(kv, capacity_pages=8,
                                 registry=StatRegistry())
        slot = kv.alloc()
        kv.ensure_pages(slot, 8)
        toks = np.arange(8, dtype=np.int32)
        sig = (1, 2, 4, "float32", 4)
        entry = store.insert(toks, kv.slot_page_ids(slot), sig)
        kv.free(slot)                        # store refs keep pages live
        assert kv.pool.pages_in_use == 2
        store.unpin(entry)
        assert store.evict_unpinned(2) == 2
        assert kv.pool.pages_in_use == 0


class TestAdmissionAndEviction:
    @pytest.mark.slow      # page-starved drain takes ~5s; admission +
    # reclamation stay covered fast by test_midstream_eviction below
    def test_pending_burst_drains_without_deadlock(self, model):
        # more requests than slots AND pages: everything must complete
        eng = _engine(model, kv_layout="paged", page_size=8,
                      num_pages=16, num_slots=2)
        rng = np.random.default_rng(17)
        reqs = [eng.submit(list(rng.integers(0, 64, 12)),
                           max_new_tokens=4) for _ in range(6)]
        outs = [r.result()["tokens"] for r in reqs]
        assert all(len(t) == 4 for t in outs)
        kv = eng._batcher.kv
        eng.drain(timeout=120)
        assert kv.pool.pages_in_use == 0

    def test_midstream_eviction_reclaims_pages(self, model):
        # two sequences whose combined growth outruns an 8-page pool:
        # the younger is evicted mid-stream, its pages return, and the
        # survivor finishes at full length
        eng = _engine(model, kv_layout="paged", page_size=8,
                      num_pages=8, num_slots=2)
        rng = np.random.default_rng(19)
        r1 = eng.submit(list(rng.integers(0, 64, 20)), max_new_tokens=30)
        r2 = eng.submit(list(rng.integers(0, 64, 20)), max_new_tokens=30)
        results, errors = [], []
        for r in (r1, r2):
            try:
                results.append(r.result()["tokens"])
            except Exception as e:           # noqa: BLE001 -- the evicted lane's error type is the assertion
                errors.append(e)
        assert len(errors) == 1 and "page" in str(errors[0]).lower()
        assert len(results) == 1 and len(results[0]) == 30
        assert eng.registry.get(
            "serving.llm.pages_evicted_midstream") >= 1
        kv = eng._batcher.kv
        eng.drain(timeout=120)
        assert kv.pool.pages_in_use == 0


class TestSchedulerConfig:
    def test_kv_layout_validation(self):
        with pytest.raises(ValueError, match="kv_layout"):
            LLMEngineConfig(kv_layout="fancy")
        with pytest.raises(ValueError, match="page_size"):
            LLMEngineConfig(kv_layout="paged", max_seq=64, page_size=7)
        with pytest.raises(ValueError, match="num_pages"):
            LLMEngineConfig(kv_layout="paged", max_seq=64, page_size=8,
                            num_pages=4)
        with pytest.raises(ValueError, match="paged_attn_impl"):
            LLMEngineConfig(kv_layout="paged", paged_attn_impl="magic")

    def test_decoder_requires_paged_types(self, model):
        dec = GPTPagedDecoder(model, page_size=8)
        assert dec.kv_layout == "paged"
        kv = dec.new_kv(num_slots=2, max_seq=32)
        assert isinstance(kv, PagedKVCache)
        with pytest.raises(NotImplementedError):
            dec.insert_prefix(kv, 0, None, None)


class TestTunerFamily:
    def test_candidates_are_divisors(self):
        from paddle_tpu.tuner import paged_attn_candidates
        cands = [c["block_h"] for c in paged_attn_candidates(12, 64, 16)]
        assert cands and all(12 % b == 0 for b in cands)

    def test_key_and_committed_default(self):
        from paddle_tpu import tuner
        key = tuner.paged_key(4, 8, 8, "float32", platform="cpu")
        assert key == "paged_attn|cpu|float32|h4|d8|p8"
        cfg = tuner._resolve(key)
        assert cfg and cfg["block_h"] == 4   # committed default winner


class TestAuditEntrypoint:
    def test_paged_decode_step_registered(self):
        from paddle_tpu.core.audit import load_default_entrypoints
        eps = load_default_entrypoints()
        assert "llm_paged_decode_step" in eps
