"""Crash-consistent async checkpointing (docs/fault_tolerance.md, "Async
checkpointing"): atomic commit protocol, bounded-queue coalescing,
retry-then-degrade, staging invisibility, GC guards, the chaos-campaign
FaultInjector actions, and the ckpt.async.* observability surface. The
SIGKILL subprocess matrix lives in tests/test_chaos_checkpoint.py."""
import json
import os
import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.incubate.checkpoint import (
    AsyncCheckpointConfig, AsyncCheckpointer, OLD_SUFFIX, STAGING_SUFFIX,
    CheckpointIntegrityError, TrainEpochRange, cleanup_stale_staging,
    commit_checkpoint, load_sharded, newest_healthy_checkpoint,
    read_health_stamp, save_sharded, verify_checkpoint, write_health_stamp)
from paddle_tpu.incubate.checkpoint import async_ckpt as ac
from paddle_tpu.utils.resilience import (FaultInjector, FaultInjected,
                                         _reset_fault_injector_for_tests)


@pytest.fixture
def fault_spec(monkeypatch):
    """Arm PADDLE_TPU_FAULT_SPEC for this test; always reset the process-
    wide injector on both entry and exit."""
    def arm(spec):
        monkeypatch.setenv("PADDLE_TPU_FAULT_SPEC", spec)
        _reset_fault_injector_for_tests()
    _reset_fault_injector_for_tests()
    yield arm
    _reset_fault_injector_for_tests()


def _state(scale=1.0):
    return {"w": jnp.arange(16.0) * scale, "b": jnp.ones(3), "step": 1}


class TestCommitProtocol:
    def test_commit_roundtrip_and_no_staging_left(self, tmp_path):
        p = str(tmp_path / "ck")
        commit_checkpoint(_state(), p, step=5)
        verify_checkpoint(p)
        out = load_sharded(p, return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(16.0))
        assert out["step"] == 1
        assert not os.path.exists(p + STAGING_SUFFIX)

    def test_health_rides_the_commit(self, tmp_path):
        p = str(tmp_path / "ck")
        commit_checkpoint(_state(), p, healthy=False, step=9, reason="nan")
        stamp = read_health_stamp(p)
        assert stamp["healthy"] is False and stamp["reason"] == "nan"
        # the stamp is ALSO inside the manifest: removing the sidecar (the
        # old non-atomic artifact) must not lose it
        os.remove(os.path.join(p, "health.json"))
        stamp = read_health_stamp(p)
        assert stamp["healthy"] is False and stamp["reason"] == "nan"

    def test_sidecar_overrides_manifest(self, tmp_path):
        # retroactive mark-unhealthy (sentinel discovers the divergence
        # after the commit) must win over the committed manifest health
        p = str(tmp_path / "ck")
        commit_checkpoint(_state(), p, healthy=True)
        write_health_stamp(p, False, reason="post-hoc divergence")
        assert read_health_stamp(p)["healthy"] is False

    def test_plain_save_sharded_still_reads_exactly_healthy(self, tmp_path):
        # format-2 checkpoints have no health anywhere: the shim must return
        # the exact legacy default (test_sentinel.py relies on it too)
        p = str(tmp_path / "ck")
        save_sharded(_state(), p)
        assert read_health_stamp(p) == {"healthy": True}

    def test_recommit_over_existing_checkpoint(self, tmp_path):
        p = str(tmp_path / "ck")
        commit_checkpoint(_state(1.0), p)
        commit_checkpoint(_state(2.0), p)
        out = load_sharded(p, return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(16.0) * 2)

    def test_staging_dir_is_invisible_to_readers(self, tmp_path):
        committed = str(tmp_path / "snap_1")
        commit_checkpoint(_state(), committed)
        # a writer died mid-stage: full-looking checkpoint files inside a
        # *.tmp dir, newer numeric suffix than the committed one
        staging = str(tmp_path / ("snap_2" + STAGING_SUFFIX))
        commit_checkpoint(_state(2.0), str(tmp_path / "scratch"))
        os.rename(str(tmp_path / "scratch"), staging)
        assert newest_healthy_checkpoint(str(tmp_path)) == committed
        from paddle_tpu.incubate.checkpoint.sharded import _is_checkpoint_dir
        assert not _is_checkpoint_dir(staging)

    def test_cleanup_stale_staging(self, tmp_path):
        keep = str(tmp_path / "snap_1")
        commit_checkpoint(_state(), keep)
        stale = str(tmp_path / ("snap_2" + STAGING_SUFFIX))
        os.makedirs(stale)
        held = str(tmp_path / ("snap_3" + STAGING_SUFFIX))
        os.makedirs(held)
        removed = cleanup_stale_staging(str(tmp_path), held={held})
        assert removed == [stale]
        assert os.path.isdir(held) and os.path.isdir(keep)

    def test_recommit_never_has_a_zero_checkpoint_instant(self, tmp_path,
                                                          monkeypatch):
        # regression: _publish used to rmtree(final) before os.replace, so
        # a crash in between left NEITHER checkpoint. Now the old commit
        # is parked as *.old — prove the swap window always holds at least
        # one complete checkpoint by failing exactly inside it.
        p = str(tmp_path / "latest")
        commit_checkpoint(_state(1.0), p)
        real_replace = os.replace

        def exploding_replace(src, dst):
            real_replace(src, dst)
            if dst.endswith(OLD_SUFFIX):  # crash right after parking
                raise RuntimeError("synthetic crash inside the swap window")

        monkeypatch.setattr(ac.os, "replace", exploding_replace)
        with pytest.raises(RuntimeError, match="swap window"):
            commit_checkpoint(_state(2.0), p)
        monkeypatch.undo()
        # on disk: no final, but the parked old commit + the staged new one
        assert not os.path.isdir(p)
        assert os.path.isdir(p + OLD_SUFFIX)
        # the startup sweep recovers the parked commit and drops staging
        cleanup_stale_staging(str(tmp_path))
        verify_checkpoint(p)
        out = load_sharded(p, return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(16.0))  # commit #1
        assert not os.path.isdir(p + OLD_SUFFIX)
        assert not os.path.isdir(p + STAGING_SUFFIX)
        # and a clean re-commit over the recovered path still works
        commit_checkpoint(_state(3.0), p)
        out = load_sharded(p, return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(16.0) * 3)

    def test_cleanup_removes_stale_old_when_final_exists(self, tmp_path):
        p = str(tmp_path / "snap_1")
        commit_checkpoint(_state(2.0), p)
        commit_checkpoint(_state(1.0), str(tmp_path / "scratch"))
        os.rename(str(tmp_path / "scratch"), p + OLD_SUFFIX)
        removed = cleanup_stale_staging(str(tmp_path))
        assert removed == [p + OLD_SUFFIX]
        out = load_sharded(p, return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(16.0) * 2)

    def test_parked_old_dir_is_invisible_to_readers(self, tmp_path):
        committed = str(tmp_path / "snap_1")
        commit_checkpoint(_state(), committed)
        # a parked previous commit with a NEWER numeric prefix must never
        # win a restore walk over a committed sibling
        commit_checkpoint(_state(2.0), str(tmp_path / "scratch"))
        os.rename(str(tmp_path / "scratch"),
                  str(tmp_path / ("snap_2" + OLD_SUFFIX)))
        assert newest_healthy_checkpoint(str(tmp_path)) == committed
        from paddle_tpu.incubate.checkpoint.sharded import _is_checkpoint_dir
        assert not _is_checkpoint_dir(str(tmp_path / ("snap_2" + OLD_SUFFIX)))


class _BlockingWriter:
    """Monkeypatch target for async_ckpt._write_staged: parks the writer
    thread on an Event so queue behaviour is deterministic."""

    def __init__(self, real):
        self.release = threading.Event()
        self.entered = threading.Event()
        self._real = real

    def __call__(self, staging, meta, blobs, scalars, health, fsync=True):
        self.entered.set()
        assert self.release.wait(10), "test never released the writer"
        return self._real(staging, meta, blobs, scalars, health, fsync=fsync)


class TestAsyncCheckpointer:
    def test_async_commit_roundtrip(self, tmp_path):
        with AsyncCheckpointer() as ck:
            t = ck.save(_state(), str(tmp_path / "ck"), step=3)
            assert t.wait(30) and t.committed and t.error is None
        out = load_sharded(str(tmp_path / "ck"), return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(16.0))

    def test_full_queue_supersedes_oldest(self, tmp_path, monkeypatch):
        reg = StatRegistry()
        blocker = _BlockingWriter(ac._write_staged)
        monkeypatch.setattr(ac, "_write_staged", blocker)
        ck = AsyncCheckpointer(AsyncCheckpointConfig(queue_depth=2),
                               registry=reg)
        tickets = [ck.save(_state(i), str(tmp_path / f"snap_{i}"))
                   for i in range(1, 2)]
        assert blocker.entered.wait(10)  # snap_1 is now in flight
        for i in range(2, 6):  # 4 queued into depth 2 -> 2 superseded
            tickets.append(ck.save(_state(i), str(tmp_path / f"snap_{i}")))
        blocker.release.set()
        ck.close(timeout=30)
        flags = [(t.committed, t.superseded) for t in tickets]
        assert flags == [(True, False),   # in-flight when the queue filled
                         (False, True), (False, True),  # coalesced away
                         (True, False), (True, False)]
        assert reg.get("ckpt.async.superseded") == 2
        assert reg.get("ckpt.async.commits") == 3
        # superseded snapshots were never published
        assert not os.path.exists(str(tmp_path / "snap_2"))
        assert os.path.exists(str(tmp_path / "snap_5"))

    def test_wait_blocks_until_in_flight_commit_lands(self, tmp_path,
                                                      monkeypatch):
        # regression: drain/SIGTERM must wait for the in-flight commit, not
        # just an empty queue
        blocker = _BlockingWriter(ac._write_staged)
        monkeypatch.setattr(ac, "_write_staged", blocker)
        with AsyncCheckpointer() as ck:
            t = ck.save(_state(), str(tmp_path / "ck"))
            assert blocker.entered.wait(10)
            assert ck.wait(timeout=0.2) is False  # still in flight
            blocker.release.set()
            assert ck.wait(timeout=30) is True
            assert t.committed
        verify_checkpoint(str(tmp_path / "ck"))

    def test_held_paths_cover_pending_and_staging(self, tmp_path,
                                                  monkeypatch):
        blocker = _BlockingWriter(ac._write_staged)
        monkeypatch.setattr(ac, "_write_staged", blocker)
        ck = AsyncCheckpointer(AsyncCheckpointConfig(queue_depth=2))
        p1, p2 = str(tmp_path / "snap_1"), str(tmp_path / "snap_2")
        ck.save(_state(), p1)
        assert blocker.entered.wait(10)
        ck.save(_state(), p2)
        held = ck.held_paths()
        assert {p1, p1 + STAGING_SUFFIX, p2,
                p2 + STAGING_SUFFIX} <= held
        blocker.release.set()
        ck.close(timeout=30)
        assert ck.held_paths() == set()

    def test_save_after_close_raises(self, tmp_path):
        ck = AsyncCheckpointer()
        ck.close()
        with pytest.raises(RuntimeError):
            ck.save(_state(), str(tmp_path / "ck"))

    def test_writer_death_recorded_and_respawned(self, tmp_path,
                                                 monkeypatch):
        # the synthetic SystemExit below is the *point* — keep pytest's
        # thread excepthook from promoting it to a session-level warning
        monkeypatch.setattr(threading, "excepthook", lambda args: None)
        reg = StatRegistry()
        ck = AsyncCheckpointer(registry=reg)
        boom = {"armed": True}
        real_process = ck._process

        def exploding(item):
            if boom["armed"]:
                boom["armed"] = False
                raise SystemExit("synthetic writer death")
            return real_process(item)

        ck._process = exploding
        t1 = ck.save(_state(), str(tmp_path / "a"))
        deadline = time.monotonic() + 10
        while reg.get("ckpt.async.writer_deaths") == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert reg.get("ckpt.async.writer_deaths") == 1
        # the next save respawns the writer and commits normally
        t2 = ck.save(_state(), str(tmp_path / "b"))
        assert t2.wait(30) and t2.committed
        assert reg.get("ckpt.async.writer_restarts") == 1
        assert not t1.committed  # the dying writer took t1 with it
        ck.close(timeout=30)

    def test_on_commit_failure_keeps_ticket_committed(self, tmp_path):
        # the checkpoint is durably published before on_commit runs: a
        # failing callback must not flip the ticket or count as a failed
        # checkpoint (it used to re-_finish(error=...) and bump errors)
        reg = StatRegistry()
        with AsyncCheckpointer(registry=reg) as ck:
            with pytest.warns(UserWarning, match="on_commit"):
                t = ck.save(_state(), str(tmp_path / "ck"),
                            on_commit=lambda: 1 / 0)
                assert t.wait(30)
        assert t.committed and t.error is None
        assert reg.get("ckpt.async.commits") == 1
        assert reg.get("ckpt.async.errors") == 0
        assert reg.get("ckpt.async.on_commit_errors") == 1
        verify_checkpoint(str(tmp_path / "ck"))

    def test_ticket_finish_is_write_once(self):
        from paddle_tpu.incubate.checkpoint import SaveTicket
        t = SaveTicket("p", 1)
        t._finish(committed=True)
        t._finish(error=RuntimeError("late failure"))
        assert t.committed and t.error is None and t.done

    def test_observability_surface(self, tmp_path):
        reg = StatRegistry()
        with AsyncCheckpointer(registry=reg) as ck:
            ck.save(_state(), str(tmp_path / "ck")).wait(30)
        assert reg.get("ckpt.async.saves") == 1
        assert reg.get("ckpt.async.commits") == 1
        for hist in ("ckpt.async.enqueue_ms", "ckpt.async.fetch_ms",
                     "ckpt.async.write_ms", "ckpt.async.commit_ms"):
            assert reg.histogram(hist)["count"] >= 1
        # histograms on the DEFAULT registry render into /metricsz
        from paddle_tpu.core import monitor
        from paddle_tpu.observability.metrics import render_prometheus
        with AsyncCheckpointer() as ck:
            ck.save(_state(), str(tmp_path / "ck2")).wait(30)
        text = render_prometheus()
        assert "paddle_tpu_ckpt_async_commits_total" in text
        assert "paddle_tpu_ckpt_async_write_ms" in text


class TestMultiHost:
    def test_multihost_commit_is_cooperative(self, tmp_path, monkeypatch):
        # regression: the atomic protocol staged every process into the
        # SAME <path>.tmp and rmtree'd the final dir — on a shared
        # filesystem one host destroyed its peers' shards. Multi-host must
        # keep save_sharded's per-host-file protocol: simulate two hosts
        # sequentially and prove neither touches the other's files.
        import jax
        barriers = []
        monkeypatch.setattr(ac, "_barrier", lambda: barriers.append(1))
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        p = str(tmp_path / "ck")

        monkeypatch.setattr(jax, "process_index", lambda: 0)
        commit_checkpoint(_state(), p, step=1)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        commit_checkpoint(_state(), p, step=1)
        names = set(os.listdir(p))
        assert {"metadata_0.json", "metadata_1.json",
                "shards_0.npz", "shards_1.npz"} <= names
        # shared sidecars come from process 0 only (scalars written once)
        assert "scalars.json" in names and "health.json" in names
        # no dir-level staging was ever used
        assert not os.path.exists(p + STAGING_SUFFIX)
        assert not os.path.exists(p + OLD_SUFFIX)
        assert len(barriers) == 2  # the sync commit is collective

        # a re-save from one host must leave the peer's files intact
        # (this is exactly what rmtree(final) used to destroy)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        commit_checkpoint(_state(), p, step=2)
        names = set(os.listdir(p))
        assert "metadata_1.json" in names and "shards_1.npz" in names
        verify_checkpoint(p)
        out = load_sharded(p, return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(16.0))

    def test_multihost_health_rides_the_manifest(self, tmp_path,
                                                 monkeypatch):
        import jax
        monkeypatch.setattr(ac, "_barrier", lambda: None)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        p = str(tmp_path / "ck")
        commit_checkpoint(_state(), p, healthy=False, step=7, reason="nan")
        # proc 1 writes no sidecar, but its manifest carries the verdict
        assert not os.path.exists(os.path.join(p, "health.json"))
        stamp = read_health_stamp(p)
        assert stamp["healthy"] is False and stamp["reason"] == "nan"

    def test_multihost_torn_manifestless_write_is_detected(self, tmp_path,
                                                           monkeypatch):
        import jax
        monkeypatch.setattr(ac, "_barrier", lambda: None)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        p = str(tmp_path / "ck")
        commit_checkpoint(_state(), p)
        # simulate a peer that died after its shard archive but before its
        # manifest: checksummed files all verify, and the torn peer state
        # is detectable the moment its manifest appears truncated/absent —
        # here the nastier variant: manifest present, archive truncated
        with open(os.path.join(p, "shards_0.npz"), "r+b") as f:
            f.truncate(os.path.getsize(os.path.join(p, "shards_0.npz")) // 2)
        with pytest.raises(CheckpointIntegrityError):
            verify_checkpoint(p)

    def test_coordinator_dies_between_host_commits(self, tmp_path,
                                                   monkeypatch):
        # The host-loss window: host 1 committed fully (shards + manifest),
        # then the coordinator (proc 0) was SIGKILLed after publishing its
        # shard archive but before its manifest landed. Every file present
        # passes its own checksum — only the per-host commit-marker
        # accounting can see that proc 0's slices would restore as zeros.
        import jax
        monkeypatch.setattr(ac, "_barrier", lambda: None)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        p = str(tmp_path / "ck")
        commit_checkpoint(_state(), p, step=3)

        monkeypatch.setattr(jax, "process_index", lambda: 0)
        died = RuntimeError("SIGKILL between shard publish and manifest")

        real_replace = os.replace

        def dying_replace(src, dst):
            if os.path.basename(dst).startswith("metadata_0"):
                raise died
            return real_replace(src, dst)

        monkeypatch.setattr(ac.os, "replace", dying_replace)
        with pytest.raises(RuntimeError):
            commit_checkpoint(_state(), p, step=3)
        monkeypatch.undo()

        names = set(os.listdir(p))
        assert "shards_0.npz" in names and "metadata_0.json" not in names
        with pytest.raises(CheckpointIntegrityError,
                           match="without a committing manifest"):
            verify_checkpoint(p)
        # the restore walk treats it like any torn checkpoint: skipped,
        # not zero-filled
        assert newest_healthy_checkpoint(str(tmp_path)) is None

    def test_partial_manifest_health_stamp_is_tolerated(self, tmp_path,
                                                        monkeypatch):
        # proc 0 (the only sidecar writer) died pre-marker: no health.json,
        # no metadata_0.json. read_health_stamp must fall back to the
        # surviving host's inline manifest health instead of raising.
        import jax
        monkeypatch.setattr(ac, "_barrier", lambda: None)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        p = str(tmp_path / "ck")
        commit_checkpoint(_state(), p, healthy=False, step=9, reason="nan")
        # coordinator debris: its shard landed, its manifest did not
        with open(os.path.join(p, "shards_0.npz"), "wb") as f:
            f.write(b"not a real archive")
        assert not os.path.exists(os.path.join(p, "health.json"))
        stamp = read_health_stamp(p)
        assert stamp["healthy"] is False and stamp["reason"] == "nan"
        # and a garbage manifest from the dead host must not break the
        # health read either (it is skipped, not fatal)
        with open(os.path.join(p, "metadata_0.json"), "w") as f:
            f.write("{torn")
        stamp = read_health_stamp(p)
        assert stamp["healthy"] is False

    def test_cleanup_sweeps_dead_cohorts_tmp_files(self, tmp_path,
                                                   monkeypatch):
        # A cohort member SIGKILLed mid-stage leaves .tmp_* FILES inside
        # the shared checkpoint dir (per-file staging — there is no
        # dir-level .tmp to rename away multi-host). The startup sweep
        # must remove them without ever touching committed files, and
        # readers must never mistake them for shards or manifests.
        import jax
        monkeypatch.setattr(ac, "_barrier", lambda: None)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        root = tmp_path / "job"
        p = root / "ck"
        commit_checkpoint(_state(), str(p), step=1)
        debris = [p / ".tmp_shards_1.npz", p / ".tmp_metadata_1.json",
                  root / ".tmp_shards_9.npz"]
        for d in debris:
            d.write_bytes(b"dead cohort stage")
        removed = cleanup_stale_staging(str(root))
        assert {str(d) for d in debris} <= set(removed)
        for d in debris:
            assert not d.exists()
        # committed state untouched and loadable
        verify_checkpoint(str(p))
        out = load_sharded(str(p), return_tensor=False)
        np.testing.assert_allclose(out["w"], np.arange(16.0))
        # held dirs are protected from the file sweep
        (p / ".tmp_shards_1.npz").write_bytes(b"live stage")
        cleanup_stale_staging(str(root), held={str(p)})
        assert (p / ".tmp_shards_1.npz").exists()


class TestFaultActions:
    def test_new_actions_parse_and_fire_verbatim(self):
        fi = FaultInjector("ckpt_shard_write:2:torn_write,"
                           "ckpt_fetch:1:disk_full,"
                           "ckpt_pre_rename:1:slow_io,"
                           "ckpt_post_rename:1:kill_during_commit")
        assert fi.armed("ckpt_shard_write")
        assert fi.fire("ckpt_shard_write") is None       # occurrence 1
        assert fi.fire("ckpt_shard_write") == "torn_write"
        assert fi.fire("ckpt_pre_rename") == "slow_io"
        assert fi.fire("ckpt_fetch") == "disk_full"
        # kill_during_commit is the crash alias — NOT fired here (it would
        # os._exit the test process); the chaos matrix proves it end to end

    def test_occurrence_counting_is_per_site(self):
        fi = FaultInjector("ckpt_fetch:3:disk_full")
        assert fi.fire("ckpt_fetch") is None
        assert fi.fire("ckpt_shard_write") is None  # different site
        assert fi.fire("ckpt_fetch") is None
        assert fi.fire("ckpt_fetch") == "disk_full"
        assert fi.fire("ckpt_fetch") is None        # one-shot

    def test_disk_full_raises_enospc_at_site(self, tmp_path, fault_spec):
        import errno
        fault_spec("ckpt_shard_write:1:disk_full")
        with pytest.raises(OSError) as ei:
            commit_checkpoint(_state(), str(tmp_path / "ck"))
        assert ei.value.errno == errno.ENOSPC
        # nothing was published
        assert newest_healthy_checkpoint(str(tmp_path)) is None

    def test_torn_write_is_caught_by_verification(self, tmp_path,
                                                  fault_spec):
        fault_spec("ckpt_shard_write:1:torn_write")
        p = str(tmp_path / "snap_2")
        commit_checkpoint(_state(), p)  # publishes a torn archive
        with pytest.raises(CheckpointIntegrityError):
            verify_checkpoint(p)
        with pytest.raises(CheckpointIntegrityError):
            load_sharded(p)
        # and the healthy-walk falls back past it (disarm via an EMPTY
        # spec — a bare reset would re-parse the still-set env var and
        # tear this write too)
        fault_spec("")
        good = str(tmp_path / "snap_1")
        commit_checkpoint(_state(), good)
        with pytest.warns(UserWarning, match="skipping checkpoint"):
            assert newest_healthy_checkpoint(str(tmp_path)) == good

    def test_slow_io_stalls_the_commit(self, tmp_path, fault_spec,
                                       monkeypatch):
        monkeypatch.setattr(ac, "SLOW_IO_SECONDS", 0.3)
        fault_spec("ckpt_pre_rename:1:slow_io")
        t0 = time.perf_counter()
        commit_checkpoint(_state(), str(tmp_path / "ck"))
        assert time.perf_counter() - t0 >= 0.3

    def test_async_retries_transient_then_commits(self, tmp_path,
                                                  fault_spec):
        reg = StatRegistry()
        fault_spec("ckpt_shard_write:1:disk_full")
        cfg = AsyncCheckpointConfig(max_attempts=3, backoff=0.01)
        with AsyncCheckpointer(cfg, registry=reg) as ck:
            t = ck.save(_state(), str(tmp_path / "ck"))
            assert t.wait(30) and t.committed
        assert reg.get("ckpt.async.retries") == 1
        assert reg.get("ckpt.async.degraded_skips") == 0
        verify_checkpoint(str(tmp_path / "ck"))

    def test_async_degrades_to_skip_after_retries(self, tmp_path,
                                                  fault_spec):
        reg = StatRegistry()
        fault_spec("ckpt_shard_write:1:disk_full,"
                   "ckpt_shard_write:2:disk_full,"
                   "ckpt_shard_write:3:disk_full")
        cfg = AsyncCheckpointConfig(max_attempts=3, backoff=0.01)
        with AsyncCheckpointer(cfg, registry=reg) as ck:
            with pytest.warns(UserWarning, match="skipped"):
                t = ck.save(_state(), str(tmp_path / "snap_1"))
                assert t.wait(30)
                assert not t.committed and t.error is not None
                # the step loop lives on: the NEXT save commits fine
                t2 = ck.save(_state(), str(tmp_path / "snap_2"))
                assert t2.wait(30) and t2.committed
        assert reg.get("ckpt.async.degraded_skips") == 1
        assert reg.get("ckpt.async.retries") == 2
        assert not os.path.exists(str(tmp_path / "snap_1"))
        assert not os.path.exists(str(tmp_path / "snap_1") + STAGING_SUFFIX)
        verify_checkpoint(str(tmp_path / "snap_2"))


class TestIntegration:
    def test_train_epoch_range_async(self, tmp_path):
        # same state whether saved sync or async+atomic
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        import paddle_tpu.optimizer as optim

        def make():
            paddle.seed(11)
            net = nn.Linear(4, 2)
            opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
            return net, opt

        def epoch_step(net, opt):
            x = paddle.ones((2, 4))
            loss = paddle.mean(net(x) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()

        net1, opt1 = make()
        r1 = TrainEpochRange(4, "async_job", model=net1, optimizer=opt1,
                             checkpoint_path=str(tmp_path / "a"),
                             async_save=True)
        for _ in r1:
            epoch_step(net1, opt1)
        r1.wait()

        # resume run restores bit-identical params from the async commits
        net2, opt2 = make()
        r2 = TrainEpochRange(4, "async_job", model=net2, optimizer=opt2,
                             checkpoint_path=str(tmp_path / "a"))
        assert r2.restored_epoch == 3
        np.testing.assert_array_equal(net1.weight.numpy(),
                                      net2.weight.numpy())

    def test_epoch_gc_skips_writer_held_paths(self, tmp_path):
        r = TrainEpochRange(10, "gc_job",
                            checkpoint_path=str(tmp_path / "g"))
        held_dir = r._epoch_dir(1)
        os.makedirs(held_dir)
        os.makedirs(r._epoch_dir(2))

        class FakeSaver:
            def held_paths(self):
                return {held_dir}
        r._saver = FakeSaver()
        r._keep_last = 1
        r._gc(9)  # would normally sweep both epoch_1 and epoch_2
        assert os.path.isdir(held_dir)          # writer-held: protected
        assert not os.path.isdir(r._epoch_dir(2))

    def test_rollback_atomic_snapshot_closes_stamp_window(self, tmp_path,
                                                          fault_spec):
        # kill between rename and (the former) stamp write: with the stamp
        # folded into the commit there is no such window — prove the stamp
        # is present the instant the snapshot dir exists
        from paddle_tpu.sentinel.rollback import CheckpointRollback

        class Store:
            def __init__(self):
                self.w = jnp.arange(4.0)

            def state_dict(self):
                return {"w": self.w}

            def set_state_dict(self, s):
                self.w = s["w"]

        st = Store()
        rb = CheckpointRollback(str(tmp_path / "snaps"), model=st,
                                keep_last=2)
        d = rb.snapshot(1, healthy=False, reason="spike")
        assert os.path.isdir(d)
        assert read_health_stamp(d)["healthy"] is False
        assert json.load(open(os.path.join(
            d, "metadata_0.json")))["health"]["reason"] == "spike"

    def test_rollback_async_snapshots_restore(self, tmp_path):
        from paddle_tpu.sentinel.rollback import CheckpointRollback

        class Store:
            def __init__(self):
                self.w = jnp.zeros(4)

            def state_dict(self):
                return {"w": self.w}

            def set_state_dict(self, s):
                self.w = s["w"]

        st = Store()
        rb = CheckpointRollback(str(tmp_path / "snaps"), model=st,
                                keep_last=2, async_save=True)
        for step in (1, 2):
            st.w = jnp.full((4,), float(step))
            rb.snapshot(step)
        st.w = jnp.full((4,), 99.0)  # diverged state
        # restore waits for the queued async snapshots first
        assert rb.restore_newest_healthy() == 2
        np.testing.assert_allclose(np.asarray(st.w._data), np.full(4, 2.0))

    def test_rollback_mark_unhealthy_applies_to_in_flight_snapshot(
            self, tmp_path, monkeypatch):
        # regression: mark_unhealthy only stamped an EXISTING dir, so a
        # verdict against a still-queued async snapshot was silently
        # dropped and restore_newest_healthy could restore it
        from paddle_tpu.sentinel.rollback import CheckpointRollback

        class Store:
            def __init__(self):
                self.w = jnp.arange(4.0)

            def state_dict(self):
                return {"w": self.w}

            def set_state_dict(self, s):
                self.w = s["w"]

        blocker = _BlockingWriter(ac._write_staged)
        monkeypatch.setattr(ac, "_write_staged", blocker)
        st = Store()
        rb = CheckpointRollback(str(tmp_path / "snaps"), model=st,
                                keep_last=4, async_save=True)
        d = rb.snapshot(1)
        assert blocker.entered.wait(10)   # snapshot 1 is mid-write
        rb.mark_unhealthy(1, reason="divergence caught mid-save")
        assert not os.path.isdir(d)       # verdict raced the publish
        blocker.release.set()
        rb.wait(30)
        # the commit hook applied the pending verdict post-publish
        stamp = read_health_stamp(d)
        assert stamp["healthy"] is False
        assert stamp["reason"] == "divergence caught mid-save"
        assert rb.restore_newest_healthy() is None
        rb._ckpt.close(30)

    def test_epoch_mark_unhealthy_applies_to_in_flight_save(
            self, tmp_path, monkeypatch):
        blocker = _BlockingWriter(ac._write_staged)
        monkeypatch.setattr(ac, "_write_staged", blocker)
        r = TrainEpochRange(3, "mu_job",
                            checkpoint_path=str(tmp_path / "mu"),
                            async_save=True)
        r.save(0)
        assert blocker.entered.wait(10)
        r.mark_unhealthy(0, reason="nan epoch")
        blocker.release.set()
        r.wait()
        stamp = read_health_stamp(r._epoch_dir(0))
        assert stamp["healthy"] is False and stamp["reason"] == "nan epoch"
        r._saver.close(30)

    def test_fault_tolerance_callback_async_save(self, tmp_path):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi.callbacks import FaultToleranceCallback

        class FakeModel:
            def __init__(self):
                paddle.seed(3)
                self.network = nn.Linear(4, 2)
                self._optimizer = None

        cb = FaultToleranceCallback(str(tmp_path / "ft"), guard=object(),
                                    async_save=True)
        cb._guard = None  # let on_train_begin build a real guard
        cb.set_model(FakeModel())
        cb.on_train_begin()
        cb.on_epoch_end(0)
        cb.on_train_end()
        state = load_sharded(str(tmp_path / "ft" / "latest"))
        np.testing.assert_array_equal(
            state["model"]["weight"].numpy(), cb.model.network.weight.numpy())
        cb._guard.uninstall()


@pytest.mark.slow
@pytest.mark.timeout_s(240)
def test_async_hides_most_of_sync_overhead():
    """ISSUE 10 acceptance bar: the async path hides >= 80% of the
    synchronous checkpoint wall time from the train step (reduced scales
    of the tools/bench_ckpt.py sweep; the CLI gate is --bench-ckpt)."""
    from tools.bench_ckpt import run_bench
    out = run_bench(scales=(1 << 18, 1 << 20), steps=10, save_every=2,
                    step_ms=40.0)
    assert out["hidden_fraction_overall"] >= 0.8, out
