"""Tier-1 tests for the concurrency lint (PTA006/PTA007) and the
attribute-aware call graph underneath it.

Covers the issue's acceptance gates:

- each seeded finding class fires on tests/fixtures/{race,sighandler}_
  seeded.py — and only those classes, nothing extra;
- the attribute-aware call graph resolves ``self.``-dispatch, aliased
  imports and ``Class().method()`` chains, and stays conservative on
  unresolvable dynamic dispatch (precise walks drop the edge, the jit
  walk keeps its name-based over-approximation);
- ``--format sarif`` emits the SARIF 2.1.0 shape; ``--strict`` promotes
  warnings to gating findings.
"""
import json
import os
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.analyze.core import Project, run_rules  # noqa: E402
from tools.analyze.rules import rules_by_code      # noqa: E402

RULES = rules_by_code()

RACE_FIXTURE = "tests/fixtures/race_seeded.py"
SIG_FIXTURE = "tests/fixtures/sighandler_seeded.py"


def _mini(tmp_path, files):
    roots = set()
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        roots.add(rel.split("/")[0])
    return Project(str(tmp_path), sorted(roots))


def _findings(tmp_path, files, codes=("PTA006", "PTA007")):
    project = _mini(tmp_path, files)
    return project, run_rules(project, [RULES[c] for c in codes])


def _driver(args):
    return subprocess.run([sys.executable, "-m", "tools.analyze"] + args,
                          cwd=REPO, capture_output=True, text=True)


# -- seeded-fixture acceptance ------------------------------------------------

def test_race_fixture_fires_both_pta006_classes_and_nothing_else():
    proc = _driver(["--baseline", "none", "--rule", "PTA006",
                    "--rule", "PTA007", "--json", RACE_FIXTURE])
    assert proc.returncode == 1, proc.stdout
    found = json.loads(proc.stdout)["findings"]
    assert [f["rule"] for f in found] == ["PTA006", "PTA006"]
    blob = " | ".join(f["message"] for f in found)
    assert "check-then-act on `self.items`" in blob
    assert "`self.count` is guarded by `self._lock`" in blob
    assert "written here without it" in blob


def test_sighandler_fixture_fires_every_pta007_class_and_nothing_else():
    proc = _driver(["--baseline", "none", "--rule", "PTA006",
                    "--rule", "PTA007", "--json", SIG_FIXTURE])
    assert proc.returncode == 1, proc.stdout
    found = json.loads(proc.stdout)["findings"]
    assert all(f["rule"] == "PTA007" for f in found)
    assert len(found) == 4
    blob = " | ".join(f["message"] for f in found)
    assert "logging call in signal context" in blob
    assert "acquires `_STATE_LOCK` in signal context" in blob
    assert "`time.sleep()` blocks" in blob
    assert "`raise` escaping a signal handler" in blob
    by_sev = sorted(f["severity"] for f in found)
    assert by_sev == ["error", "error", "warning", "warning"]


def test_repo_is_clean_for_concurrency_rules():
    """The issue's acceptance command: exit 1 on the seeded fixtures
    (above), exit 0 on the repo after the fixes/noqas."""
    proc = _driver(["--rule", "PTA006", "--rule", "PTA007",
                    "paddle_tpu", "tools"])
    assert proc.returncode == 0, proc.stdout


# -- attribute-aware call graph ----------------------------------------------

def test_callgraph_resolves_self_dispatch(tmp_path):
    project = _mini(tmp_path, {"pkg/w.py": """\
        import threading

        class Worker:
            def __init__(self):
                self._t = threading.Thread(target=self._run)

            def _run(self):
                self._step()

            def _step(self):
                pass
    """})
    names = {f.qualname for f in project.callgraph.thread_reachable()}
    assert {"Worker._run", "Worker._step"} <= names


def test_callgraph_resolves_aliased_imports(tmp_path):
    project = _mini(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/util.py": """\
            def helper():
                pass
        """,
        "pkg/main.py": """\
            import threading
            from . import util as u

            def entry():
                u.helper()

            threading.Thread(target=entry).start()
        """,
    })
    names = {f.qualname for f in project.callgraph.thread_reachable()}
    assert "entry" in names
    assert "helper" in names  # via the `u` module alias


def test_callgraph_resolves_class_call_method_chain(tmp_path):
    project = _mini(tmp_path, {"pkg/box.py": """\
        import threading

        class Box:
            def __init__(self):
                self.v = 0

            def fill(self):
                self.v = 1

        def entry():
            Box().fill()

        threading.Thread(target=entry).start()
    """})
    names = {f.qualname for f in project.callgraph.thread_reachable()}
    assert "Box.fill" in names
    assert "Box.__init__" in names  # constructor edge on the precise walk


def test_callgraph_stays_conservative_on_dynamic_dispatch(tmp_path):
    """Unresolvable `obj.method()`: the precise (thread) walk drops the
    edge — no hallucinated PTA006 through a name collision — while the
    jit walk keeps the name-based over-approximation so PTA001 never
    misses a tracer leak (no regression vs. the name-based graph)."""
    project = _mini(tmp_path, {"pkg/dyn.py": """\
        import threading
        import jax

        class Store:
            def take(self):
                return 1

        def thread_entry(q):
            q.take()        # q's type is unknown

        @jax.jit
        def jit_entry(q):
            q.take()        # same call shape, jit side

        threading.Thread(target=thread_entry).start()
    """})
    graph = project.callgraph
    thread = {f.qualname for f in graph.thread_reachable()}
    assert "thread_entry" in thread
    assert "Store.take" not in thread          # precise: edge dropped
    jit = {f.qualname for f in graph.reachable()}
    assert "Store.take" in jit                 # conservative fallback kept


# -- PTA006 semantics ---------------------------------------------------------

COND_ALIAS = """\
    import threading

    class Q:
        def __init__(self):
            self._lock = threading.Lock()
            self._not_empty = threading.Condition(self._lock)
            self._items = []

        def put(self, x):
            with self._lock:
                self._items.append(x)

        def take(self):
            with self._not_empty:
                return self._items.pop()   # same mutex as _lock: fine

        def peek_racy(self):
            return self._items[0]

    def run():
        Q().take()
        Q().peek_racy()

    threading.Thread(target=run).start()
"""


def test_pta006_condition_variable_aliases_into_its_lock(tmp_path):
    _, findings = _findings(tmp_path, {"pkg/q.py": COND_ALIAS})
    assert len(findings) == 1, [f.message for f in findings]
    assert "peek_racy" not in findings[0].message
    assert findings[0].line == 18  # the self._items[0] read


def test_pta006_cross_class_access_to_guarded_attr(tmp_path):
    _, findings = _findings(tmp_path, {"pkg/x.py": """\
        import threading

        class Inner:
            def __init__(self):
                self._lock = threading.Lock()
                self.hits = 0

            def bump(self):
                with self._lock:
                    self.hits += 1

        class Outer:
            def __init__(self):
                self._inner = Inner()
                self._t = threading.Thread(target=self._loop)

            def _loop(self):
                return self._inner.hits    # Inner's lock not held
    """})
    assert len(findings) == 1, [f.message for f in findings]
    assert "`self._inner.hits` is lock-guarded inside `Inner`" \
        in findings[0].message


def test_pta006_init_writes_are_exempt(tmp_path):
    _, findings = _findings(tmp_path, {"pkg/i.py": """\
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0          # unlocked write in __init__: fine

            def bump(self):
                with self._lock:
                    self.n += 1

        def run():
            C().bump()

        threading.Thread(target=run).start()
    """})
    assert findings == []


def test_pta007_rlock_downgrades_to_warning(tmp_path):
    _, findings = _findings(tmp_path, {"pkg/r.py": """\
        import signal
        import threading

        _RL = threading.RLock()

        def handler(signum, frame):
            with _RL:
                pass

        signal.signal(signal.SIGTERM, handler)
    """})
    assert len(findings) == 1
    assert findings[0].rule == "PTA007"
    assert findings[0].severity == "warning"
    assert "reentrant" in findings[0].message


# -- driver: sarif + strict ---------------------------------------------------

def test_sarif_output_has_the_2_1_0_shape(tmp_path):
    out = tmp_path / "a.sarif"
    proc = _driver(["--baseline", "none", "--rule", "PTA006",
                    "--rule", "PTA007", "--format", "sarif",
                    "--output", str(out), RACE_FIXTURE, SIG_FIXTURE])
    assert proc.returncode == 1
    doc = json.loads(out.read_text())
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "paddle-tpu-analyze"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == ["PTA006", "PTA007"]
    for r in driver["rules"]:
        assert set(r) >= {"id", "name", "shortDescription",
                          "defaultConfiguration"}
    results = run["results"]
    assert len(results) == 6
    for res in results:
        assert res["ruleId"] in ("PTA006", "PTA007")
        assert res["level"] in ("error", "warning")
        assert rule_ids[res["ruleIndex"]] == res["ruleId"]
        assert res["message"]["text"]
        (loc,) = res["locations"]
        phys = loc["physicalLocation"]
        assert phys["artifactLocation"]["uri"].startswith("tests/fixtures/")
        assert phys["region"]["startLine"] >= 1
        assert phys["region"]["startColumn"] >= 1
        assert res["baselineState"] == "new"
        assert res["partialFingerprints"]["pta/v1"]


def test_strict_promotes_warnings_to_gating():
    # the sighandler fixture's blocking/raise findings are warnings:
    # without --strict they do not gate once the errors are excluded
    args = ["--baseline", "none", "--rule", "PTA006", SIG_FIXTURE]
    assert _driver(args).returncode == 0   # PTA006 finds nothing there
    base = ["--baseline", "none", "--rule", "PTA007", "--json", SIG_FIXTURE]
    payload = json.loads(_driver(base).stdout)
    warn_only = [f for f in payload["findings"]
                 if f["severity"] == "warning"]
    assert warn_only, "fixture should produce warning-severity findings"
    # errors present -> exit 1 either way; strictness is visible in counts
    strict = json.loads(_driver(base + ["--strict"]).stdout)
    assert strict["counts"]["gating"] == strict["counts"]["new"]
    lax = json.loads(_driver(base).stdout)
    assert lax["counts"]["gating"] == lax["counts"]["new"] - len(warn_only)


def test_regen_baseline_alias(tmp_path):
    src = tmp_path / "pkg"
    src.mkdir()
    (src / "m.py").write_text("import numpy as np\n\n"
                              "def f(x):\n    return np.asarray(x)\n")
    proc = _driver(["--root", str(tmp_path), "--baseline", "bl.json",
                    "--regen-baseline", "pkg"])
    assert proc.returncode == 0, proc.stderr
    assert (tmp_path / "bl.json").is_file()
