"""VOC2012 / Flowers / VOCDetection datasets + detection transforms on
synthesized fixtures (reference: python/paddle/vision/datasets/voc2012.py,
flowers.py; detection ingest = PaddleDetection VOCDataSet capability)."""
import io
import os
import tarfile

import numpy as np
import pytest

from paddle_tpu.vision.datasets import (VOC2012, Flowers, VOCDetection,
                                        VOC_CLASSES)
from paddle_tpu.vision.transforms import (
    DetCompose, ResizeImage, RandomFlipImage, NormalizeBox, BoxXYXY2XYWH,
    PadBox, NormalizeImage, Permute)


def _png_bytes(arr):
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    return buf.getvalue()


def _jpg_bytes(arr):
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG")
    return buf.getvalue()


def _add(tf, name, data):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


@pytest.fixture
def voc_tar(tmp_path):
    rng = np.random.RandomState(0)
    path = tmp_path / "VOCtrainval_tiny.tar"
    with tarfile.open(path, "w") as tf:
        names = ["2007_000032", "2007_000033", "2007_000039"]
        _add(tf, "VOCdevkit/VOC2012/ImageSets/Segmentation/trainval.txt",
             ("\n".join(names) + "\n").encode())
        _add(tf, "VOCdevkit/VOC2012/ImageSets/Segmentation/val.txt",
             (names[0] + "\n").encode())
        _add(tf, "VOCdevkit/VOC2012/ImageSets/Segmentation/train.txt",
             ("\n".join(names[:2]) + "\n").encode())
        for n in names:
            img = rng.randint(0, 255, (24, 32, 3), dtype=np.uint8)
            seg = rng.randint(0, 21, (24, 32), dtype=np.uint8)
            _add(tf, f"VOCdevkit/VOC2012/JPEGImages/{n}.jpg",
                 _jpg_bytes(img))
            _add(tf, f"VOCdevkit/VOC2012/SegmentationClass/{n}.png",
                 _png_bytes(seg))
    return str(path)


def test_voc2012_modes_and_samples(voc_tar):
    ds = VOC2012(data_file=voc_tar, mode="train")
    assert len(ds) == 3           # trainval list, reference mode quirk
    img, seg = ds[0]
    assert img.shape == (24, 32, 3) and seg.shape == (24, 32)
    assert seg.max() <= 20
    assert len(VOC2012(data_file=voc_tar, mode="valid")) == 1
    assert len(VOC2012(data_file=voc_tar, mode="test")) == 2
    with pytest.raises(ValueError):
        VOC2012(data_file=voc_tar, mode="bogus")
    with pytest.raises(RuntimeError, match="download"):
        VOC2012(data_file=None)
    # transform applies to the image only
    ds_t = VOC2012(data_file=voc_tar, mode="train",
                   transform=lambda im: im.astype(np.float32) / 255.0)
    img_t, _ = ds_t[1]
    assert img_t.dtype == np.float32 and img_t.max() <= 1.0


@pytest.fixture
def flowers_files(tmp_path):
    import scipy.io as scio
    rng = np.random.RandomState(1)
    n = 8
    data_file = tmp_path / "102flowers.tgz"
    with tarfile.open(data_file, "w:gz") as tf:
        for i in range(1, n + 1):
            img = rng.randint(0, 255, (20, 20, 3), dtype=np.uint8)
            _add(tf, "jpg/image_%05d.jpg" % i, _jpg_bytes(img))
    labels = rng.randint(1, 103, (1, n)).astype(np.uint8)
    scio.savemat(tmp_path / "imagelabels.mat", {"labels": labels})
    scio.savemat(tmp_path / "setid.mat", {
        "tstid": np.arange(1, 6)[None], "trnid": np.array([[6, 7]]),
        "valid": np.array([[8]])})
    return (str(data_file), str(tmp_path / "imagelabels.mat"),
            str(tmp_path / "setid.mat"), labels[0])


def test_flowers_splits_and_labels(flowers_files):
    data, lab, setid, labels = flowers_files
    tr = Flowers(data_file=data, label_file=lab, setid_file=setid,
                 mode="train")
    assert len(tr) == 5            # reference swap: train = tstid
    img, y = tr[2]
    assert img.shape == (20, 20, 3)
    assert y.dtype == np.int64 and y[0] == labels[3 - 1]  # index 3, 1-based
    te = Flowers(data_file=data, label_file=lab, setid_file=setid,
                 mode="test")
    assert len(te) == 2
    va = Flowers(data_file=data, label_file=lab, setid_file=setid,
                 mode="valid", transform=lambda im: im[:10])
    assert va[0][0].shape == (10, 20, 3)
    with pytest.raises(RuntimeError, match="download"):
        Flowers()


def _write_voc_devkit(root, n=3):
    rng = np.random.RandomState(2)
    base = os.path.join(root, "VOC2012")
    os.makedirs(os.path.join(base, "JPEGImages"))
    os.makedirs(os.path.join(base, "Annotations"))
    os.makedirs(os.path.join(base, "ImageSets", "Main"))
    names = []
    for i in range(n):
        name = "im%04d" % i
        names.append(name)
        h, w = 40 + 8 * i, 60
        img = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
        from PIL import Image
        Image.fromarray(img).save(
            os.path.join(base, "JPEGImages", name + ".jpg"))
        objs = []
        for b in range(i + 1):     # i+1 boxes
            x1, y1 = 1 + 10 * b, 1 + 5 * b
            cls = VOC_CLASSES[(i + b) % 20]
            objs.append(f"""
  <object><name>{cls}</name><difficult>{b % 2}</difficult>
    <bndbox><xmin>{x1}</xmin><ymin>{y1}</ymin>
            <xmax>{x1 + 12}</xmax><ymax>{y1 + 9}</ymax></bndbox>
  </object>""")
        xml = (f"<annotation><size><width>{w}</width><height>{h}</height>"
               f"</size>{''.join(objs)}</annotation>")
        with open(os.path.join(base, "Annotations", name + ".xml"), "w") as f:
            f.write(xml)
    with open(os.path.join(base, "ImageSets", "Main", "train.txt"),
              "w") as f:
        f.write("\n".join(names) + "\n")
    return names


def test_voc_detection_parse(tmp_path):
    _write_voc_devkit(str(tmp_path))
    ds = VOCDetection(str(tmp_path), mode="train")
    assert len(ds) == 3
    img, boxes, labels, diff = ds[2]
    assert img.shape == (56, 60, 3)
    assert boxes.shape == (3, 4) and labels.shape == (3,)
    # 1-based inclusive -> 0-based: xmin 1 -> 0
    np.testing.assert_allclose(boxes[0], [0, 0, 12, 9])
    assert diff.tolist() == [0, 1, 0]
    ds_nd = VOCDetection(str(tmp_path), mode="train", keep_difficult=False)
    _, b2, _, d2 = ds_nd[2]
    assert b2.shape == (2, 4) and (d2 == 0).all()


def test_det_transform_pipeline(tmp_path):
    _write_voc_devkit(str(tmp_path))
    pipe = DetCompose([
        ResizeImage(64),
        RandomFlipImage(prob=1.0),
        NormalizeBox(),
        BoxXYXY2XYWH(),
        PadBox(10),
        NormalizeImage(),
        Permute()])
    ds = VOCDetection(str(tmp_path), mode="train", transform=pipe)
    img, boxes, labels, diff = ds[1]
    assert img.shape == (3, 64, 64) and img.dtype == np.float32
    assert boxes.shape == (10, 4) and labels.shape == (10,)
    # two real boxes, rest zero-padded (w==h==0 marks empty slot)
    assert (boxes[:2, 2] > 0).all() and (boxes[2:] == 0).all()
    assert (boxes >= 0).all() and (boxes <= 1).all()
    # flip invariant: center-x mirrored, width/height preserved
    raw = VOCDetection(str(tmp_path), mode="train")
    img0, b0, l0, _ = raw[1]
    h, w = img0.shape[:2]
    scale = 64.0
    exp_w = (b0[0, 2] - b0[0, 0]) * scale / w / scale
    np.testing.assert_allclose(boxes[0, 2], exp_w, rtol=1e-5)
    np.testing.assert_allclose(labels[:2], l0, atol=0)
