"""Tier-1 tests for the kernel-safety/fusion-audit tier (PTA013 Pallas
source lint + PTA014 HLO fusion-miss audit) and the satellites that
shipped with it (winner VMEM fail-fast, --changed-only trace scoping,
the fusion_audit.json artifact, the unfused_boundary_bytes gate).

Layers:

- seeded-fixture acceptance: every PTA013 finding class fires on
  ``tests/fixtures/pallas_seeded.py`` and each is killable by noqa and
  by a baseline entry; the real Pallas surface stays clean;
- the committed-winner VMEM fail-fast (ISSUE satellite 1): every
  ``default_winners.json`` entry passes its space.py model;
- pure fusion-miss passes against hand-built HLO dumps (shape bytes,
  boundary classification, ranking, the fully-fused negative);
- PTA014 rule behaviour over synthetic reports (the PTA012 test seam);
- gate + driver satellites: unfused_boundary_bytes regression fails
  ``check_audit_regression``, --changed-only scopes the trace tier via
  the audit registry's import closures, and --fusion-report emits the
  standalone artifact from the memoized report.
"""
import dataclasses
import json
import os
import re
import subprocess
import sys

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import jax.numpy as jnp                                 # noqa: E402

from paddle_tpu.core.audit import AuditSpec             # noqa: E402
from paddle_tpu.tuner import space                      # noqa: E402
from tools.analyze import trace as trace_mod            # noqa: E402
from tools.analyze.trace import (EntrypointStats,       # noqa: E402
                                 TraceReport, audit_spec, passes)
from tools.analyze.core import (Project, filter_noqa,   # noqa: E402
                                baseline_payload, split_findings)
from tools.analyze.rules import rules_by_code           # noqa: E402
from tools.analyze.rules.pta013_pallas_safety import (  # noqa: E402
    iter_winner_footprints, parse_winner_key)
from tools.analyze.rules.pta014_fusion_miss import (    # noqa: E402
    FUSION_MISS_BYTES_THRESHOLD)

PTA005 = rules_by_code()["PTA005"]
PTA013 = rules_by_code()["PTA013"]
PTA014 = rules_by_code()["PTA014"]

FIXTURE = os.path.join("tests", "fixtures", "pallas_seeded.py")


def _driver(args):
    return subprocess.run([sys.executable, "-m", "tools.analyze"] + args,
                          cwd=REPO, capture_output=True, text=True)


# -- PTA013 seeded-fixture acceptance ----------------------------------------

def test_pallas_fixture_fires_every_pta013_class_and_nothing_else():
    proc = _driver(["--baseline", "none", "--rule", "PTA013", "--json",
                    FIXTURE])
    assert proc.returncode == 1, proc.stdout
    found = json.loads(proc.stdout)["findings"]
    assert all(f["rule"] == "PTA013" for f in found)
    assert len(found) == 4, [f["message"] for f in found]
    blob = " | ".join(f["message"] for f in found)
    # (a) unguarded grid division
    assert "no divisibility guard" in blob
    assert "`block_q`" in blob
    # (b) VMEM-busting BlockSpecs (32 MiB vs the ~12.8 MiB budget)
    assert "over the 13421772 byte budget" in blob
    assert "32.0 MiB" in blob
    # (c) bf16 accumulator
    assert "allocated as bfloat16" in blob
    # (d) missing interpret lane — a warning, the rest are errors
    assert "without an `interpret=` keyword" in blob
    sev = sorted(f["severity"] for f in found)
    assert sev == ["error", "error", "error", "warning"]
    # the clean_* controls (guard idiom, sanitize provenance, f32+int32
    # accumulators) stay finding-free
    lines = {f["line"] for f in found}
    src = open(os.path.join(REPO, FIXTURE)).read().splitlines()
    for i, text in enumerate(src, 1):
        if "clean_" in text and "def " in text:
            assert not any(i <= ln <= i + 20 for ln in lines), text


def test_pta013_killable_by_noqa(tmp_path):
    src = open(os.path.join(REPO, FIXTURE)).read()
    patched = []
    for line in src.splitlines():
        if ("PTA013(a)" in line or "pl.pallas_call(" in line
                or "jnp.bfloat16" in line):
            line += "  # noqa: PTA013 -- seeded fixture, deliberate"
        patched.append(line)
    p = tmp_path / "pallas_noqa.py"
    p.write_text("\n".join(patched) + "\n")
    proc = _driver(["--baseline", "none", "--rule", "PTA013", "--json",
                    str(p)])
    assert proc.returncode == 0, proc.stdout
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["counts"]["suppressed"] == 4


def test_pta013_killable_by_baseline(tmp_path):
    bl = tmp_path / "baseline.json"
    wrote = _driver(["--baseline", str(bl), "--write-baseline",
                     "--rule", "PTA013", FIXTURE])
    assert wrote.returncode == 0, wrote.stdout
    proc = _driver(["--baseline", str(bl), "--rule", "PTA013", "--json",
                    FIXTURE])
    assert proc.returncode == 0, proc.stdout
    payload = json.loads(proc.stdout)
    assert payload["counts"]["new"] == 0
    assert payload["counts"]["baselined"] == 4


def test_pta013_clean_on_real_pallas_surface():
    # the acceptance bar: the hand-written kernel families use the
    # sanctioned idioms (mod-guard + raise, _sanitize_* provenance, f32
    # accumulators, interpret lanes) and must stay finding-free
    proc = _driver([
        "--baseline", "none", "--rule", "PTA013", "--json",
        "paddle_tpu/ops",
        "paddle_tpu/distributed/fleet/sequence_parallel.py",
        "paddle_tpu/tuner"])
    assert proc.returncode == 0, proc.stdout
    assert json.loads(proc.stdout)["findings"] == []


# -- VMEM models + committed winners (ISSUE satellite 1) ----------------------

def test_blockspec_vmem_bytes_model():
    assert space.blockspec_vmem_bytes([(128, 64)]) == 128 * 64 * 4
    assert space.blockspec_vmem_bytes(
        [(128, 64), (64, 64)], itemsize=2) == (128 * 64 + 64 * 64) * 2
    assert space.blockspec_vmem_bytes([]) == 0


def test_every_committed_winner_fits_its_vmem_model():
    # a stale hand-edited winner must fail fast here, not OOM Mosaic on
    # a TPU — including the handcrafted flash_bwd/paged_attn entries
    # that have never run on hardware
    rows = list(iter_winner_footprints(REPO))
    assert len(rows) >= 14, rows
    fams = {fam for _, fam, _, _ in rows}
    assert {"flash_fwd", "flash_bwd", "ring_flash", "ring_flash_bwd",
            "paged_attn"} <= fams
    for key, fam, bytes_, budget in rows:
        assert bytes_ <= budget, \
            f"{key} ({fam}): {bytes_} bytes over the {budget} VMEM budget"


def test_winner_key_parsing():
    p = parse_winner_key("flash_fwd|tpu|bfloat16|d64|q4096|k4096|c1")
    assert p["family"] == "flash_fwd" and p["dtype"] == "bfloat16"
    assert (p["d"], p["q"], p["k"]) == (64, 4096, 4096)
    p = parse_winner_key("paged_attn|tpu|bfloat16|h12|d64|p16")
    assert (p["h"], p["d"], p["p"]) == (12, 64, 16)
    # families with no VMEM model are skipped, not silently mis-modeled
    assert parse_winner_key("nms|cpu|k64") is None


# -- fusion-miss passes (HLO text level) --------------------------------------

HLO_DOC = """\
HloModule jit_step, entry_computation_layout={(f32[128,512]{1,0})->f32[128,512]{1,0}}

%fused_computation (param_0.1: f32[128,512]) -> f32[128,512] {
  %param_0.1 = f32[128,512]{1,0} parameter(0)
  ROOT %multiply.1 = f32[128,512]{1,0} multiply(%param_0.1, %param_0.1)
}

ENTRY %main.9 (Arg_0.1: f32[128,512]) -> f32[128,512] {
  %Arg_0.1 = f32[128,512]{1,0} parameter(0)
  %fusion = f32[128,512]{1,0} fusion(%Arg_0.1), kind=kLoop, calls=%fused_computation
  %w = f32[512,512]{1,0} constant({...})
  %dot.3 = f32[128,512]{1,0} dot(%fusion, %w), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %reduce.1 = f32[128]{0} reduce(%dot.3, %Arg_0.1), dimensions={1}, to_apply=%add_comp
  ROOT %tanh.1 = f32[128,512]{1,0} tanh(%dot.3)
}
"""


def test_shape_bytes_parses_dtypes_and_tuples():
    assert passes._shape_bytes("f32[4,512]{1,0}") == 4 * 512 * 4
    assert passes._shape_bytes("bf16[8]{0}") == 16
    assert passes._shape_bytes("s8[3,3]") == 9
    assert passes._shape_bytes("pred[16]") == 16
    assert passes._shape_bytes("f32[]") == 4
    assert passes._shape_bytes("(f32[8,4]{1,0}, s32[])") == 128 + 4


def test_parse_hlo_module_structure():
    mod = passes.parse_hlo_module(HLO_DOC)
    assert mod["entry"] == "main.9"
    entry = {i["name"]: i for i in mod["computations"]["main.9"]}
    assert entry["dot.3"]["operands"] == ["fusion", "w"]
    assert entry["fusion"]["calls"] == "fused_computation"
    assert entry["tanh.1"]["bytes"] == 128 * 512 * 4
    fused = mod["computations"]["fused_computation"]
    assert [i["opcode"] for i in fused] == ["parameter", "multiply"]


def test_fusion_miss_report_classifies_and_ranks_boundaries():
    rep = passes.fusion_miss_report(HLO_DOC)
    # fusion (elementwise), dot, reduce, tanh = 4 compute regions
    assert rep["fusion_regions"] == 4
    kinds = {(m["producer"], m["consumer"]): m["kind"]
             for m in rep["top_fusion_misses"]}
    # the kLoop elementwise fusion feeding the dot is the canonical miss
    assert kinds[("fusion", "dot.3")] == "elementwise->dot"
    assert kinds[("dot.3", "tanh.1")] == "dot->elementwise"
    assert kinds[("dot.3", "reduce.1")] == "dot->elementwise"
    # ranked by producer bytes, all three cross a 256 KiB boundary
    bytes_ = [m["bytes"] for m in rep["top_fusion_misses"]]
    assert bytes_ == sorted(bytes_, reverse=True)
    assert rep["unfused_boundary_bytes"] == sum(bytes_) == 3 * 128 * 512 * 4


def test_norm_to_dot_boundary_counts():
    hlo = """\
ENTRY %main (p: f32[64,256]) -> f32[64,64] {
  %p = f32[64,256]{1,0} parameter(0)
  %reduce.2 = f32[64,256]{1,0} reduce(%p, %p), dimensions={1}, to_apply=%add
  %w2 = f32[256,64]{1,0} constant({...})
  ROOT %dot.9 = f32[64,64]{1,0} dot(%reduce.2, %w2), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
    rep = passes.fusion_miss_report(hlo)
    (miss,) = rep["top_fusion_misses"]
    assert miss["kind"] == "norm->dot"
    assert miss["bytes"] == 64 * 256 * 4


def test_fully_fused_program_reports_no_misses():
    hlo = """\
%fused_computation (p0: f32[32,32], p1: f32[32,32]) -> f32[32,32] {
  %p0 = f32[32,32]{1,0} parameter(0)
  %p1 = f32[32,32]{1,0} parameter(1)
  %dot.1 = f32[32,32]{1,0} dot(%p0, %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %tanh.2 = f32[32,32]{1,0} tanh(%dot.1)
}

ENTRY %main (a: f32[32,32], b: f32[32,32]) -> f32[32,32] {
  %a = f32[32,32]{1,0} parameter(0)
  %b = f32[32,32]{1,0} parameter(1)
  ROOT %fusion = f32[32,32]{1,0} fusion(%a, %b), kind=kOutput, calls=%fused_computation
}
"""
    rep = passes.fusion_miss_report(hlo)
    assert rep["fusion_regions"] == 1
    assert rep["unfused_boundary_bytes"] == 0
    assert rep["top_fusion_misses"] == []


def test_audit_spec_records_fusion_fields():
    def step(x, w):
        h = jnp.tanh(x)
        return jnp.maximum(h @ w, 0.0)

    spec = AuditSpec(fn=step, make_args=lambda v: (
        jnp.full((64, 64), float(v + 1)), jnp.full((64, 64), 0.5)))
    st = audit_spec("fusion_probe", spec)
    assert st.error == "", st.error
    assert st.fusion_regions > 0
    assert st.unfused_boundary_bytes >= 0
    assert st.unfused_boundary_bytes >= sum(
        m["bytes"] for m in st.top_fusion_misses)
    for m in st.top_fusion_misses:
        assert m["kind"] in ("elementwise->dot", "norm->dot",
                             "dot->elementwise")
        assert m["bytes"] > 0
    # payload round-trips the new fields (the trace-report schema)
    pl = st.payload()
    assert pl["fusion_regions"] == st.fusion_regions
    assert pl["unfused_boundary_bytes"] == st.unfused_boundary_bytes


# -- PTA014 rule over reports -------------------------------------------------

def _report_with(**overrides):
    st = EntrypointStats(name="ep", tags=("train",),
                         path=FIXTURE, line=14)
    for k, v in overrides.items():
        setattr(st, k, v)
    return TraceReport(platform="cpu", entrypoint_stats={"ep": st})


def _pta014_findings(report, monkeypatch):
    monkeypatch.setattr(trace_mod, "_LAST", report)
    return PTA014.finalize(None)


def _misses(*sizes):
    return [{"kind": "elementwise->dot", "producer": f"fusion.{i}",
             "producer_op": "fusion", "consumer": f"dot.{i}",
             "consumer_op": "dot", "bytes": b, "shape": "f32[...]"}
            for i, b in enumerate(sizes)]


def test_pta014_fires_over_threshold_with_ranked_misses(monkeypatch):
    fs = _pta014_findings(_report_with(
        fusion_regions=12,
        unfused_boundary_bytes=FUSION_MISS_BYTES_THRESHOLD + 1,
        top_fusion_misses=_misses(900000, 148577)), monkeypatch)
    assert len(fs) == 1
    assert fs[0].severity == "warning"
    assert fs[0].anchor == "trace:ep:fusion-miss"
    assert (fs[0].path, fs[0].line) == (FIXTURE, 14)
    assert "fusion.0->dot.0" in fs[0].message
    assert "--fusion-report" in fs[0].message


def test_pta014_quiet_at_or_below_threshold(monkeypatch):
    fs = _pta014_findings(_report_with(
        unfused_boundary_bytes=FUSION_MISS_BYTES_THRESHOLD,
        top_fusion_misses=_misses(FUSION_MISS_BYTES_THRESHOLD)),
        monkeypatch)
    assert fs == []


def test_pta014_skips_errored_entrypoints_and_reports_runner_loss(
        monkeypatch):
    # a build failure is PTA009's finding; PTA014 must not double-report
    fs = _pta014_findings(_report_with(
        error="boom", unfused_boundary_bytes=10 << 20), monkeypatch)
    assert fs == []
    monkeypatch.setattr(trace_mod, "_LAST", TraceReport(
        platform="unavailable", entrypoint_stats={}, error="ImportError"))
    fs = PTA014.finalize(None)
    assert len(fs) == 1
    assert fs[0].severity == "error"
    assert fs[0].anchor == "trace:runner:unavailable"


def test_pta014_killable_by_baseline(monkeypatch):
    fs = _pta014_findings(_report_with(
        unfused_boundary_bytes=2 << 20,
        top_fusion_misses=_misses(2 << 20)), monkeypatch)
    baseline = baseline_payload(fs)["findings"]
    new, baselined, expired = split_findings(fs, baseline)
    assert new == [] and len(baselined) == 1 and expired == []


def test_pta014_killable_by_noqa(tmp_path, monkeypatch):
    reg = tmp_path / "reg.py"
    reg.write_text("register_entrypoint('ep', f)"
                   "  # noqa: PTA014 -- pre-megakernel state, item-1 WIP\n")
    fs = _pta014_findings(_report_with(
        unfused_boundary_bytes=2 << 20,
        top_fusion_misses=_misses(2 << 20)), monkeypatch)
    fs = [dataclasses.replace(f, path="reg.py", line=1) for f in fs]
    project = Project(str(tmp_path), ["reg.py"])
    kept, suppressed = filter_noqa(project, fs)
    assert kept == [] and len(suppressed) == 1


def test_committed_analyzer_baseline_covers_known_fusion_misses():
    # gpt_train_step / resnet_train_step fire PTA014 today (the ROADMAP
    # item-1 backlog); their findings must be baselined so the --strict
    # --trace-audit lane stays green until the megakernel PR lands
    with open(os.path.join(REPO, "tools", "analyze",
                           "baseline.json")) as f:
        entries = json.load(f)["findings"]
    anchored = {e["message"] for e in entries.values()
                if e["rule"] == "PTA014"}
    assert any("gpt_train_step" in m for m in anchored)
    assert any("resnet_train_step" in m for m in anchored)


# -- unfused_boundary_bytes audit gate ----------------------------------------

def _gate():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_audit_regression as gate
    return gate


def test_unfused_boundary_bytes_regression_fails_gate():
    # the seeded regression of the acceptance criteria: an artificially
    # de-fused entrypoint (boundary bytes up >5%) must fail the gate
    gate = _gate()
    name = "gpt_train_step"
    counters = {"host_transfers": 0, "large_consts": 0,
                "donatable_inputs": 0, "retraces": 0,
                "fingerprint_unstable": 0, "copy_fraction": 0.0,
                "collective_bytes": 0, "collective_issues": 0,
                "unfused_boundary_bytes": 2_000_000}
    base = {name: dict(counters)}
    ok = {name: dict(counters, unfused_boundary_bytes=2_080_000)}
    bad = {name: dict(counters, unfused_boundary_bytes=2_200_000)}
    assert not any("unfused_boundary_bytes" in p
                   for p in gate.compare(base, ok))
    problems = gate.compare(base, bad)
    assert any("unfused_boundary_bytes regressed 2000000 -> 2200000" in p
               for p in problems)
    assert any("PTA014" in p for p in problems)


def test_gate_summarize_reads_fusion_fields():
    gate = _gate()
    payload = {"entrypoints": {
        gate.ENTRYPOINTS[0]: {
            "transfers": [], "large_consts": [], "donation": None,
            "trace_count": 1, "fingerprint_stable": True,
            "hlo": {"instructions": 10, "copies": 0},
            "collectives": [], "collective_bytes": 0,
            "collective_issues": [],
            "unfused_boundary_bytes": 777}}}
    cur = gate.summarize(payload)[gate.ENTRYPOINTS[0]]
    assert cur["unfused_boundary_bytes"] == 777


def test_committed_baseline_gates_gpt_fusion_bytes():
    # the acceptance bar: gpt_train_step reports a non-empty fusion-miss
    # list whose byte total the committed baseline now gates
    with open(os.path.join(REPO, "bench_audit_baseline.json")) as f:
        entries = json.load(f)["entrypoints"]
    assert entries["gpt_train_step"]["unfused_boundary_bytes"] > 0
    assert entries["resnet_train_step"]["unfused_boundary_bytes"] > 0


# -- PTA005 noqa policing for the new tiers -----------------------------------

def test_bare_pta013_noqa_policed_in_any_api_module(tmp_path):
    mod = tmp_path / "paddle_tpu" / "newkernel.py"
    mod.parent.mkdir()
    mod.write_text(
        "from __future__ import annotations\n"
        "x = 1  # noqa: PTA013\n"
        "y = 2  # noqa: PTA014 -- pre-megakernel state, tracked in item 1\n"
        "z = 3  # noqa: PTA003\n")
    project = Project(str(tmp_path), ["paddle_tpu"])
    fs = PTA005.visit_file(project.files[0], project)
    assert len(fs) == 1, [f.message for f in fs]
    assert "PTA013" in fs[0].message
    assert fs[0].anchor.startswith("noqa-hygiene:PTA013:")
    # the bare suppression cannot silence its own policing finding
    kept, suppressed = filter_noqa(project, fs)
    assert len(kept) == 1 and suppressed == []


def test_bare_pta014_noqa_policed(tmp_path):
    mod = tmp_path / "paddle_tpu" / "reg.py"
    mod.parent.mkdir()
    mod.write_text("from __future__ import annotations\n"
                   "r = 0  # noqa: PTA014\n")
    project = Project(str(tmp_path), ["paddle_tpu"])
    fs = PTA005.visit_file(project.files[0], project)
    assert len(fs) == 1
    assert fs[0].anchor.startswith("noqa-hygiene:PTA014:")


# -- --changed-only trace scoping (ISSUE satellite 2) -------------------------

def test_changed_kernel_file_scopes_to_its_entrypoints():
    names = trace_mod.scope_entrypoints(
        REPO, ["paddle_tpu/ops/paged_attention.py"])
    assert "llm_paged_decode_step" in names
    assert "resnet_train_step" not in names
    names = trace_mod.scope_entrypoints(
        REPO, ["paddle_tpu/serving/engine.py"])
    assert "serving_predict" in names
    assert "llm_paged_decode_step" not in names


def test_changed_unrelated_file_scopes_to_nothing():
    assert trace_mod.scope_entrypoints(
        REPO, ["paddle_tpu/vision/transforms.py"]) == []


def test_changed_registry_file_scopes_to_everything():
    names = trace_mod.scope_entrypoints(
        REPO, ["paddle_tpu/core/audit.py"])
    assert "resnet_train_step" in names and "serving_predict" in names
    assert len(names) >= 9


def test_set_audit_scope_empty_runs_zero_entrypoints():
    try:
        trace_mod.set_audit_scope([])
        rep = trace_mod.run_audit()
        assert rep.error == ""
        assert rep.entrypoint_stats == {}
    finally:
        trace_mod.set_audit_scope(None)
        trace_mod._reset_for_tests()


# -- fusion_audit.json artifact (ISSUE satellite 6) ---------------------------

def test_fusion_report_artifact_from_memoized_report(tmp_path, monkeypatch):
    import tools.analyze.__main__ as main_mod
    heavy = EntrypointStats(name="heavy", path=FIXTURE, line=1,
                            fusion_regions=12,
                            unfused_boundary_bytes=5_000_000,
                            top_fusion_misses=_misses(5_000_000))
    light = EntrypointStats(name="light", path=FIXTURE, line=2,
                            fusion_regions=3,
                            unfused_boundary_bytes=100)
    broken = EntrypointStats(name="broken", error="boom")
    monkeypatch.setattr(trace_mod, "_LAST", TraceReport(
        platform="cpu", entrypoint_stats={
            "heavy": heavy, "light": light, "broken": broken}))
    out = tmp_path / "fusion_audit.json"
    rc = main_mod.main(["--only", "PTA014", "--baseline", "none",
                        "--fusion-report", str(out), FIXTURE])
    assert rc == 0  # PTA014 findings are warnings; they gate only --strict
    doc = json.loads(out.read_text())
    assert doc["ranking"] == ["heavy", "light"]  # errored excluded
    assert doc["entrypoints"]["heavy"]["unfused_boundary_bytes"] == 5_000_000
    assert doc["entrypoints"]["heavy"]["top_fusion_misses"][0]["bytes"] \
        == 5_000_000
    assert "broken" not in doc["entrypoints"]


@pytest.mark.slow
def test_fusion_report_artifact_end_to_end(tmp_path):
    # the full driver lane: trace one cheap entrypoint in a fresh
    # process and emit the standalone artifact
    out = tmp_path / "fusion_audit.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PTA_TRACE_ENTRYPOINTS="serving_predict")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--only", "PTA014",
         "--baseline", "none", "--fusion-report", str(out), "paddle_tpu"],
        cwd=REPO, capture_output=True, text=True, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc["ranking"] == ["serving_predict"]
    st = doc["entrypoints"]["serving_predict"]
    assert st["fusion_regions"] > 0


# -- docs / listing consistency (ISSUE satellite 3) ---------------------------

def test_new_rules_listed_and_documented():
    proc = _driver(["--list-rules"])
    assert proc.returncode == 0
    lines = {ln.split()[0]: ln for ln in proc.stdout.splitlines() if ln}
    assert "PTA013" in lines and "PTA014" in lines
    assert "[trace tier]" not in lines["PTA013"]   # AST tier: default run
    assert "[trace tier]" in lines["PTA014"]
    docs = open(os.path.join(REPO, "docs", "static_analysis.md")).read()
    for code in ("PTA013", "PTA014"):
        assert re.search(rf"^\| {code} \|", docs, re.M), code
    # the worked-true-positive chapter exists
    assert "Kernel safety & fusion audit" in docs
