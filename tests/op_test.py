"""OpTest harness: numpy-reference op checking + numeric gradient checking.

Port of the reference test discipline (reference:
python/paddle/fluid/tests/unittests/op_test.py:270 OpTest,
check_output_with_place :1076, check_grad :1405, get_numeric_gradient :110):
every op test supplies numpy inputs and a numpy-computed expected output;
gradients are validated against central differences.
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import Tensor


def check_output(op_fn, np_fn, inputs, atol=1e-4, rtol=1e-4, kwargs=None):
    """Run `op_fn(*tensors, **kwargs)` and compare to `np_fn(*numpy_arrays)`."""
    kwargs = kwargs or {}
    tensors = [paddle.to_tensor(a) for a in inputs]
    out = op_fn(*tensors, **kwargs)
    expected = np_fn(*inputs)
    _compare(out, expected, atol, rtol)


def _compare(out, expected, atol, rtol):
    if isinstance(out, (list, tuple)):
        assert isinstance(expected, (list, tuple)), "output arity mismatch"
        for o, e in zip(out, expected):
            _compare(o, e, atol, rtol)
        return
    got = out.numpy() if isinstance(out, Tensor) else np.asarray(out)
    expected = np.asarray(expected)
    assert got.shape == expected.shape, f"shape {got.shape} vs {expected.shape}"
    np.testing.assert_allclose(got.astype(np.float64) if got.dtype != bool else got,
                               expected.astype(np.float64) if expected.dtype != bool else expected,
                               atol=atol, rtol=rtol)


def check_grad(op_fn, inputs, grad_idx=0, eps=1e-3, atol=1e-2, rtol=1e-2,
               kwargs=None, reduce_to_scalar=True):
    """Central-difference gradient check (reference: op_test.py
    get_numeric_gradient :110): analytic grad from the tape vs numeric grad of
    sum(op(x)) w.r.t. inputs[grad_idx]."""
    kwargs = kwargs or {}
    inputs = [np.asarray(a, np.float32) for a in inputs]
    tensors = [paddle.to_tensor(a) for a in inputs]
    for t in tensors:
        t.stop_gradient = False

    out = op_fn(*tensors, **kwargs)
    loss = out.sum() if reduce_to_scalar else out
    loss.backward()
    analytic = tensors[grad_idx].grad.numpy().astype(np.float64)

    def f(x_flat):
        args = [a.copy() for a in inputs]
        args[grad_idx] = x_flat.reshape(inputs[grad_idx].shape).astype(np.float32)
        ts = [paddle.to_tensor(a) for a in args]
        o = op_fn(*ts, **kwargs)
        return float(o.sum().numpy())

    x0 = inputs[grad_idx].astype(np.float64).reshape(-1)
    numeric = np.zeros_like(x0)
    for i in range(x0.size):
        xp = x0.copy(); xp[i] += eps
        xm = x0.copy(); xm[i] -= eps
        numeric[i] = (f(xp) - f(xm)) / (2 * eps)
    numeric = numeric.reshape(inputs[grad_idx].shape)
    np.testing.assert_allclose(analytic, numeric, atol=atol, rtol=rtol)
