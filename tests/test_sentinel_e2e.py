"""End-to-end proofs for the numerical-anomaly sentinel (the ISSUE's
acceptance criteria):

- fault-injected NaN under ``skip_step``: training finishes with finite
  loss and params BIT-IDENTICAL to a run that skipped that step's update;
- under ``rollback``: the last healthy checkpoint is restored and training
  completes;
- the healthy guarded step performs exactly ONE host sync — asserted two
  ways: the PTA002 analyzer finds nothing unsuppressed in the sentinel's
  hot modules (one sanctioned ``# noqa: PTA002`` fetch in guard.py), and
  the ``sentinel.host_syncs`` counter equals the guarded-step count over a
  whole run;
- the elastic supervisor does NOT restart a ``DIVERGENCE_EXIT_CODE`` halt
  (deterministic divergence must not burn the restart budget);
- ``slow`` lane: the microbench overhead budget (guarded ≤ baseline + 5%).
"""
import os
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, sentinel
from paddle_tpu import optimizer as optim
from paddle_tpu.core import monitor
from paddle_tpu.distributed.elastic import DIVERGENCE_EXIT_CODE
from paddle_tpu.distributed.launch import ElasticSupervisor
from paddle_tpu.utils import resilience


NAN_STEP = 3          # 1-based fire count == 0-based sentinel step 2
TOTAL_STEPS = 8


def _data():
    rng = np.random.RandomState(42)
    xs = rng.randn(TOTAL_STEPS, 8, 6).astype("float32")
    ys = rng.randn(TOTAL_STEPS, 8, 2).astype("float32")
    return xs, ys


def _job(ladder, tmp_path=None, **cfg_kw):
    paddle.seed(1234)
    net = nn.Linear(6, 2)
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    rb = None
    if tmp_path is not None:
        rb = sentinel.CheckpointRollback(str(tmp_path / "snaps"), model=net,
                                         optimizer=opt)
    cfg_kw.setdefault("warmup_steps", 10_000)
    s = sentinel.Sentinel(sentinel.SentinelConfig(ladder=ladder, **cfg_kw),
                          optimizer=opt, rollback=rb)
    return net, opt, rb, s


def _run_training(net, opt, s=None, skip_update_at=None, snapshot_rb=None,
                  snapshot_at=None):
    xs, ys = _data()
    losses = []
    for i in range(TOTAL_STEPS):
        x = paddle.to_tensor(xs[i])
        y = paddle.to_tensor(ys[i])
        loss = paddle.mean((net(x) - y) ** 2)
        loss.backward()
        if s is not None:
            s.observe(loss=loss, batch=([x], [y]))
        if skip_update_at is not None and i == skip_update_at:
            opt.clear_grad()    # reference run: drop this step's update
        else:
            opt.step()
            opt.clear_grad()
        losses.append(float(loss))
        if snapshot_rb is not None and i == snapshot_at:
            snapshot_rb.snapshot(i)
    return losses


@pytest.fixture(autouse=True)
def _fresh_injector_and_stats():
    resilience._reset_fault_injector_for_tests()
    for k in list(monitor.stats_with_prefix("sentinel.")):
        monitor.default_registry().reset(k)
    yield
    resilience._reset_fault_injector_for_tests()


class TestSkipStepE2E:
    def test_injected_nan_skip_is_bit_identical_to_manual_skip(
            self, monkeypatch):
        # run A: sentinel + injected NaN grads at the NAN_STEP-th step
        monkeypatch.setenv("PADDLE_TPU_FAULT_SPEC", f"grads:{NAN_STEP}:nan")
        resilience._reset_fault_injector_for_tests()
        net_a, opt_a, _, s = _job(("skip_step",))
        losses_a = _run_training(net_a, opt_a, s)
        monkeypatch.delenv("PADDLE_TPU_FAULT_SPEC")
        resilience._reset_fault_injector_for_tests()

        assert all(np.isfinite(losses_a))
        assert np.all(np.isfinite(net_a.weight.numpy()))
        assert monitor.stat_get("sentinel.nan_steps") == 1
        assert monitor.stat_get("sentinel.skipped_steps") == 1

        # run B: no sentinel, no injection — manually skip the same update
        net_b, opt_b, _, _ = _job(("skip_step",))
        opt_b._sentinel = None  # _job attached one; run B is unguarded
        losses_b = _run_training(net_b, opt_b, skip_update_at=NAN_STEP - 1)

        assert np.array_equal(net_a.weight.numpy(), net_b.weight.numpy())
        assert np.array_equal(net_a.bias.numpy(), net_b.bias.numpy())
        # healthy steps produced identical losses too (the NaN batch's loss
        # itself was finite in run A — only the grads were poisoned)
        np.testing.assert_array_equal(losses_a, losses_b)

    def test_one_host_sync_per_guarded_step_over_a_run(self):
        net, opt, _, s = _job(("skip_step",))
        syncs0 = monitor.stat_get("sentinel.host_syncs")
        _run_training(net, opt, s)
        assert monitor.stat_get("sentinel.host_syncs") == \
            syncs0 + TOTAL_STEPS


class TestRollbackE2E:
    def test_rollback_restores_last_healthy_and_completes(
            self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FAULT_SPEC", f"grads:{NAN_STEP}:nan")
        resilience._reset_fault_injector_for_tests()
        net, opt, rb, s = _job(("rollback",), tmp_path=tmp_path)
        # snapshot after step 1 (0-based), NaN hits at 0-based step 2
        losses = _run_training(net, opt, s, snapshot_rb=rb, snapshot_at=1)
        assert all(np.isfinite(losses))
        assert np.all(np.isfinite(net.weight.numpy()))
        assert monitor.stat_get("sentinel.rollbacks") == 1
        assert s.last_report is not None  # run ended with a report
        assert rb.steps() == [1]  # the restore landed on snap_1

    def test_rollback_skips_unhealthy_snapshot_e2e(self, tmp_path):
        net, opt, rb, s = _job(("rollback",), tmp_path=tmp_path)
        xs, ys = _data()
        x, y = paddle.to_tensor(xs[0]), paddle.to_tensor(ys[0])

        def one(poison=False):
            loss = paddle.mean((net(x) - y) ** 2)
            loss.backward()
            if poison:
                sentinel.poison_grads(opt)
            opt.step()
            opt.clear_grad()

        one()
        rb.snapshot(0)
        w0 = net.weight.numpy().copy()
        one()
        rb.snapshot(1)
        rb.mark_unhealthy(1, reason="post-hoc divergence discovery")
        one(poison=True)    # triggers rollback — must land on snap_0
        assert s.last_report.rolled_back_to == 0
        np.testing.assert_array_equal(net.weight.numpy(), w0)


class TestHostSyncBudgetStatic:
    def test_pta002_clean_with_one_sanctioned_fetch(self):
        """The healthy guarded step's ONE host sync, statically: the
        analyzer scans the sentinel's hot modules; everything must be
        clean except the single justified noqa in guard.py's probe."""
        from tools.analyze.core import Project, run_rules, filter_noqa
        from tools.analyze.rules import rules_by_code
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        project = Project(repo, ["paddle_tpu/sentinel"])
        findings = run_rules(project,
                             [rules_by_code()["PTA002"]])
        kept, suppressed = filter_noqa(project, findings)
        assert kept == [], f"unsuppressed host syncs in hot path: {kept}"
        sup_files = {f.path for f in suppressed}
        assert sup_files == {"paddle_tpu/sentinel/guard.py"}
        assert len(suppressed) == 1  # exactly the one sanctioned fetch


class TestSupervisorDivergenceHalt:
    def test_divergence_exit_is_not_restarted(self, tmp_path, capsys):
        script = tmp_path / "diverged.py"
        script.write_text(textwrap.dedent(f"""
            import sys
            sys.exit({DIVERGENCE_EXIT_CODE})
        """))
        sup = ElasticSupervisor(
            ["127.0.0.1:0"], str(script), [],
            max_restarts=3, grace_period=5.0,
            restart_backoff=0.05, poll_interval=0.05)
        rc = sup.run()
        assert rc == DIVERGENCE_EXIT_CODE
        assert sup.restarts_used == 0       # no budget burned
        assert sup._restart_counts == {}    # and no respawn at all
        err = capsys.readouterr().err
        assert "numerical" in err and "not restarting" in err

    def test_crash_code_still_restarts(self, tmp_path):
        # guard against the guard: 119 is special, 118/120 are not
        marker = tmp_path / "ran"
        script = tmp_path / "crash.py"
        script.write_text(textwrap.dedent(f"""
            import os, sys
            m = {str(marker)!r}
            if not os.path.exists(m):
                open(m, "w").write("x")
                sys.exit(118)
            sys.exit(0)
        """))
        sup = ElasticSupervisor(
            ["127.0.0.1:0"], str(script), [],
            max_restarts=2, grace_period=5.0,
            restart_backoff=0.05, poll_interval=0.05)
        assert sup.run() == 0
        assert sup.restarts_used == 1


@pytest.mark.slow
class TestOverheadBudget:
    def test_guarded_step_overhead_within_budget(self, tmp_path):
        """ISSUE acceptance: ≤5% step-time overhead on the microbench.
        CPU timing is noisy, so take the best of three bench runs before
        judging — a real regression fails all three."""
        import json
        from tools import bench_sentinel_overhead as bench
        best = None
        for _ in range(3):
            out = str(tmp_path / "bench.json")
            bench.main(["--steps", "40", "--warmup", "8",
                        "--hidden", "256", "--json", out])
            with open(out) as f:
                doc = json.load(f)
            pct = doc["guarded_overhead_pct"]
            best = pct if best is None else min(best, pct)
            if best <= doc["budget_pct"]:
                break
        assert best <= 5.0, f"guarded overhead {best:.2f}% > 5% budget"
