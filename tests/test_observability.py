"""paddle_tpu.observability (ISSUE 8 acceptance): span tracer fast path and
nesting, Prometheus /metricsz exposition conformance, Perfetto round-trip
from a real instrumented training run, StatRegistry snapshot consistency
under write load, flight-recorder dump schema (+ sentinel-halt e2e in a
subprocess), StepMeter/compiled_flops accounting, and the PTA005
span-fastpath lint.

``slow`` lane: MFU agreement with bench.py's analytic ResNet-50 constant,
and the ≤2% disabled-tracing overhead budget via tools/bench_observability.
"""
import json
import os
import re
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu import observability as obs
from paddle_tpu import optimizer as optim
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.observability import export, flight, metrics, stepmeter, tracer
from paddle_tpu.static import InputSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


@pytest.fixture(autouse=True)
def _tracing_off_between_tests():
    """Tests toggle the module-level gate; never leak it into the suite."""
    yield
    tracer.disable()
    tracer.default_tracer().clear()
    flight.disarm()
    flight.default_recorder().clear()


# -- span tracer --------------------------------------------------------------

class TestTracer:
    def test_disabled_returns_shared_noop(self):
        assert not tracer.is_enabled()
        s1 = tracer.span("train/step")
        s2 = tracer.span("anything", {"k": 1})
        assert s1 is s2 is tracer.NOOP_SPAN  # zero-alloc fast path
        with s1 as inner:
            inner.set_attr("ignored", 1)     # API parity, still no-op
        assert tracer.default_tracer().spans() == []

    def test_nesting_depth_and_containment(self):
        tracer.enable()
        with tracer.span("outer"):
            with tracer.span("inner", {"k": "v"}):
                pass
        spans = tracer.default_tracer().spans()
        by_name = {s["name"]: s for s in spans}
        assert by_name["outer"]["depth"] == 0
        assert by_name["inner"]["depth"] == 1
        assert by_name["inner"]["attrs"] == {"k": "v"}
        # child interval nested inside the parent's
        o, i = by_name["outer"], by_name["inner"]
        assert o["ts_ns"] <= i["ts_ns"]
        assert i["ts_ns"] + i["dur_ns"] <= o["ts_ns"] + o["dur_ns"] + 1

    def test_ring_capacity_and_dropped_counter(self):
        t = tracer.SpanTracer(capacity=4)
        for i in range(7):
            with t.span_always(f"s{i}"):
                pass
        spans = t.spans()
        assert [s["name"] for s in spans] == ["s3", "s4", "s5", "s6"]
        assert t.dropped == 3
        assert t.drain() == spans and t.spans() == []

    def test_exception_records_error_attr(self):
        t = tracer.SpanTracer()
        with pytest.raises(ValueError):
            with t.span_always("boom"):
                raise ValueError("x")
        (s,) = t.spans()
        assert s["attrs"]["error"] == "ValueError"

    def test_thread_local_stacks(self):
        tracer.enable()
        done = threading.Event()

        def other():
            with tracer.span("thread-b"):
                done.wait(5)

        th = threading.Thread(target=other)
        with tracer.span("thread-a"):
            th.start()
            time.sleep(0.02)     # b's span opens while a's is live
            done.set()
            th.join()
        by_name = {s["name"]: s for s in tracer.default_tracer().spans()}
        # concurrent spans on separate threads are both roots
        assert by_name["thread-a"]["depth"] == 0
        assert by_name["thread-b"]["depth"] == 0
        assert by_name["thread-a"]["tid"] != by_name["thread-b"]["tid"]


# -- Prometheus exposition ----------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_:]+="(\\.|[^"\\])*"'
    r'(,[a-zA-Z0-9_:]+="(\\.|[^"\\])*")*\})? '
    r'(-?\d+(\.\d+)?([eE][+-]?\d+)?|NaN|[+-]Inf)$')


def _check_exposition(text):
    """Validate text-format 0.0.4 structure: HELP/TYPE pairs once per
    family, every sample line matching the exposition grammar."""
    assert text.endswith("\n")
    helped, typed = set(), set()
    for line in text.splitlines():
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in helped, f"duplicate HELP for {name}"
            helped.add(name)
        elif line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            assert name not in typed, f"duplicate TYPE for {name}"
            assert kind in ("counter", "gauge", "summary", "histogram")
            typed.add(name)
        else:
            assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
    assert helped == typed
    return helped


class TestPrometheusExposition:
    def test_counter_gauge_summary_and_labels(self):
        reg = StatRegistry()
        reg.add("req.count", 3)                       # counter -> _total
        reg.set("queue-depth", 7)                     # gauge, '-' sanitized
        for v in (1.0, 2.0, 3.0, 4.0):
            reg.observe("lat.ms", v)
        reg.set_labeled("slots", {"state": 'bu"sy\n'}, 4)
        text = metrics.render_prometheus(reg)
        families = _check_exposition(text)
        assert families == {"paddle_tpu_req_count_total",
                            "paddle_tpu_queue_depth",
                            "paddle_tpu_lat_ms", "paddle_tpu_slots"}
        assert "# TYPE paddle_tpu_req_count_total counter" in text
        assert "paddle_tpu_req_count_total 3" in text
        assert "# TYPE paddle_tpu_queue_depth gauge" in text
        assert "# TYPE paddle_tpu_lat_ms summary" in text
        for q in ("0.5", "0.95", "0.99"):
            assert f'paddle_tpu_lat_ms{{quantile="{q}"}}' in text
        assert "paddle_tpu_lat_ms_sum 10" in text
        assert "paddle_tpu_lat_ms_count 4" in text
        # label value escaping: quote and newline survive as escapes
        assert r'paddle_tpu_slots{state="bu\"sy\n"} 4' in text

    def test_set_then_add_keeps_first_kind(self):
        reg = StatRegistry()
        reg.set("depth", 2)
        reg.add("depth", 1)   # still a gauge: first writer wins
        text = metrics.render_prometheus(reg)
        assert "# TYPE paddle_tpu_depth gauge" in text
        assert "paddle_tpu_depth 3" in text

    def test_name_collision_skips_second_family(self):
        reg = StatRegistry()
        reg.set("a.b", 1)
        reg.set("a_b", 2)     # sanitizes onto the same family name
        text = metrics.render_prometheus(reg)
        assert text.count("# TYPE paddle_tpu_a_b gauge") == 1
        _check_exposition(text)

    def test_special_values(self):
        assert metrics.format_value(float("nan")) == "NaN"
        assert metrics.format_value(float("inf")) == "+Inf"
        assert metrics.format_value(float("-inf")) == "-Inf"
        assert metrics.format_value(3.0) == "3"
        assert metrics.format_value(0.25) == "0.25"

    def test_empty_registry_renders_empty(self):
        assert metrics.render_prometheus(StatRegistry()) == ""


class TestSnapshotConsistency:
    def test_threaded_writes_never_tear_a_snapshot(self):
        """Satellite 1: one-lock snapshot. Writers hammer a histogram of
        all-1.0 values and a counter; every snapshot must satisfy
        sum == count for the histogram (a torn read of sum vs count
        breaks the equality)."""
        reg = StatRegistry()
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                reg.observe("h", 1.0)
                reg.add("c", 1)

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            deadline = time.time() + 0.5
            snaps = 0
            while time.time() < deadline:
                snap = reg.snapshot()
                if "h" in snap["histograms"]:
                    h = snap["histograms"]["h"]
                    assert h["sum"] == h["count"], snap
                    snaps += 1
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert snaps > 0


# -- Perfetto round-trip from an instrumented training run --------------------

def _tiny_model():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 2))
    model = paddle.Model(net, inputs=[InputSpec([None, 6], "float32")],
                         labels=[InputSpec([None, 2], "float32")])
    model.prepare(optim.SGD(learning_rate=0.01,
                            parameters=net.parameters()),
                  nn.loss.MSELoss())
    return model


class TestPerfettoRoundTrip:
    def test_train_run_exports_nested_loadable_trace(self, tmp_path):
        """Acceptance: a training run with tracing enabled exports a
        Perfetto-loadable trace containing nested `train/step` ->
        `jit/compile` spans."""
        model = _tiny_model()
        obs.enable()
        x = paddle.to_tensor(np.random.randn(4, 6).astype("float32"))
        y = paddle.to_tensor(np.random.randn(4, 2).astype("float32"))
        for _ in range(3):
            model.train_batch(x, y)
        path = str(tmp_path / "trace.perfetto.json")
        n = export.export_chrome_trace(path)
        assert n >= 4            # 3 steps + at least one compile span
        doc = export.load_chrome_trace(path)
        events = doc["traceEvents"]
        xev = [e for e in events if e["ph"] == "X"]
        meta = [e for e in events if e["ph"] == "M"]
        assert any(e["name"] == "process_name" for e in meta)
        assert any(e["name"] == "thread_name" for e in meta)
        steps = [e for e in xev if e["name"] == "train/step"]
        compiles = [e for e in xev if e["name"] == "jit/compile"]
        assert len(steps) == 3 and compiles
        # nesting: the compile happened inside the FIRST train/step
        first = min(steps, key=lambda e: e["ts"])
        c = compiles[0]
        assert first["ts"] <= c["ts"]
        assert c["ts"] + c["dur"] <= first["ts"] + first["dur"] + 1e-3
        assert c["args"]["depth"] >= 1
        # timestamps are monotonic non-negative µs with positive duration
        for e in xev:
            assert e["ts"] >= 0 and e["dur"] >= 0
        assert doc["otherData"]["clock"] == "perf_counter_ns"

    def test_trace_export_cli_converts_flight_dump(self, tmp_path):
        tracer.enable()
        with tracer.span("a"):
            pass
        rec = flight.FlightRecorder()
        rec.record("marker", {"x": 1})
        dump = rec.dump("unit_test", directory=str(tmp_path))
        out = str(tmp_path / "t.json")
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "trace_export.py"),
             dump, "-o", out],
            cwd=REPO, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        doc = json.load(open(out))
        assert doc["otherData"]["flight_reason"] == "unit_test"
        assert any(e.get("name") == "a" and e["ph"] == "X"
                   for e in doc["traceEvents"])


# -- /metricsz on both HTTP front-ends ----------------------------------------

def _http_get_raw(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def _serve(srv):
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv.server_address[1]


class TestMetricszHTTP:
    def test_classifier_front_end(self):
        from paddle_tpu.serving import Engine, EngineConfig
        from paddle_tpu.serving.http import make_server

        eng = Engine(lambda *a: [np.asarray(x) * 2.0 for x in a],
                     EngineConfig(max_batch=8, max_batch_delay=0.005),
                     registry=StatRegistry())
        srv = make_server(eng, port=0)
        port = _serve(srv)
        try:
            eng.submit([np.ones((2, 2), np.float32)]).result(timeout=10)
            code, ctype, text = _http_get_raw(port, "/metricsz")
            assert code == 200
            assert ctype == metrics.CONTENT_TYPE
            families = _check_exposition(text)
            assert "paddle_tpu_serving_completed_total" in families
            assert "paddle_tpu_serving_latency_ms" in families
        finally:
            srv.shutdown()
            srv.server_close()
            eng.drain()

    def test_llm_front_end(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving.http import make_server
        from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_position_embeddings=64,
                        hidden_dropout_prob=0.0,
                        attention_dropout_prob=0.0)
        net = GPTForCausalLM(cfg)
        net.eval()
        llm = LLMEngine(net, LLMEngineConfig(
            num_slots=2, max_seq=64, prefill_buckets=(8,), warmup=False,
            stat_prefix="serving.llm", measure_mfu=True),
            registry=StatRegistry())
        srv = make_server(None, port=0, llm_engine=llm)
        port = _serve(srv)
        try:
            llm.generate([1, 2, 3], max_new_tokens=4)
            code, ctype, text = _http_get_raw(port, "/metricsz")
            assert code == 200
            assert ctype == metrics.CONTENT_TYPE
            families = _check_exposition(text)
            assert "paddle_tpu_serving_llm_tokens_generated_total" \
                in families
            assert "paddle_tpu_serving_llm_decode_tick_ms" in families
            # measure_mfu published a live MFU gauge
            assert "paddle_tpu_serving_llm_mfu" in families
        finally:
            srv.shutdown()
            srv.server_close()
            llm.drain()


# -- flight recorder ----------------------------------------------------------

def _read_flight(path):
    lines = [json.loads(l) for l in open(path) if l.strip()]
    assert lines[0]["schema"] == flight.SCHEMA
    assert lines[-1]["kind"] == "stats"
    return lines


class TestFlightRecorder:
    def test_dump_schema_and_ring_bound(self, tmp_path):
        rec = flight.FlightRecorder(capacity=3)
        for i in range(5):
            rec.record("tick", {"i": i})
        assert [e["i"] for e in rec.events()] == [2, 3, 4]  # bounded ring
        reg = StatRegistry()
        reg.add("c", 2)
        reg.observe("h", 1.5)
        t = tracer.SpanTracer()
        with t.span_always("s"):
            pass
        path = rec.dump("unit", directory=str(tmp_path), registry=reg,
                        tracer=t)
        assert os.path.basename(path).startswith("flight_")
        lines = _read_flight(path)
        header = lines[0]
        assert header["reason"] == "unit" and header["pid"] == os.getpid()
        kinds = [l.get("kind") for l in lines[1:]]
        assert kinds == ["tick", "tick", "tick", "span", "stats"]
        assert lines[-1]["stats"]["c"] == 2
        assert lines[-1]["histograms"]["h"]["count"] == 1

    def test_dump_if_armed_gating(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path))
        flight.disarm()
        assert flight.dump_if_armed("nope") is None
        assert list(tmp_path.iterdir()) == []
        flight.arm()
        path = flight.dump_if_armed("yes")
        assert path is not None and os.path.exists(path)

    def test_enable_observability_arms_flight(self):
        obs.enable()
        assert flight.is_armed() and tracer.is_enabled()
        obs.disable()
        assert not flight.is_armed() and not tracer.is_enabled()

    def test_sentinel_halt_e2e_writes_flight_dump(self, tmp_path):
        """Acceptance: sentinel-halt e2e produces a schema-valid flight
        dump. NaN grads injected at step 2 trip the `halt` rung ->
        exit 119 with the armed recorder dumping first."""
        script = tmp_path / "halting_train.py"
        script.write_text(textwrap.dedent("""
            import numpy as np
            import sys
            import paddle_tpu as paddle
            from paddle_tpu import nn, sentinel
            from paddle_tpu import optimizer as optim

            paddle.seed(0)
            net = nn.Linear(6, 2)
            opt = optim.SGD(learning_rate=0.1,
                            parameters=net.parameters())
            s = sentinel.Sentinel(
                sentinel.SentinelConfig(ladder=("halt",),
                                        warmup_steps=10000),
                optimizer=opt)
            rng = np.random.RandomState(0)
            for i in range(6):
                x = paddle.to_tensor(rng.randn(8, 6).astype("float32"))
                y = paddle.to_tensor(rng.randn(8, 2).astype("float32"))
                loss = paddle.mean((net(x) - y) ** 2)
                loss.backward()
                s.observe(loss=loss)
                opt.step()
                opt.clear_grad()
            sys.exit(7)   # should never get here: step 2 halts
        """))
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO,
                   PADDLE_TPU_FAULT_SPEC="grads:2:nan",
                   PADDLE_TPU_FLIGHT="1",
                   PADDLE_TPU_FLIGHT_DIR=str(tmp_path))
        proc = subprocess.run([sys.executable, str(script)], cwd=REPO,
                              env=env, capture_output=True, text=True,
                              timeout=240)
        assert proc.returncode == 119, (proc.stdout, proc.stderr)
        dumps = list(tmp_path.glob("flight_*.jsonl"))
        assert len(dumps) == 1, proc.stderr
        assert "flight recording" in proc.stderr
        lines = _read_flight(str(dumps[0]))
        assert lines[0]["reason"] == "sentinel_halt"
        halts = [l for l in lines
                 if l.get("kind") == "sentinel" and l["action"] == "halt"]
        assert len(halts) == 1
        assert halts[0]["step"] == 1        # 0-based sentinel step
        assert "non_finite" in halts[0]["reasons"]
        assert lines[-1]["stats"]["sentinel.halts"] == 1


# -- StepMeter / MFU ----------------------------------------------------------

class TestStepMeter:
    def test_step_publishes_mfu_and_histograms(self):
        reg = StatRegistry()
        m = stepmeter.StepMeter(peak_flops=1e9, registry=reg,
                                prefix="train")
        m.set_flops_per_step(5e8)
        mfu = m.step(0.5)
        assert mfu == pytest.approx(1.0)    # 5e8 flops / 0.5s / 1e9 peak
        assert reg.get("train.mfu") == pytest.approx(1.0)
        assert reg.get("train.flops_per_step") == 5e8
        assert reg.histogram("train.step_ms")["count"] == 1
        # per-call override, and unknown-flops steps return None
        assert m.step(1.0, flops=2e9) == pytest.approx(2.0)
        assert stepmeter.StepMeter(peak_flops=1e9,
                                   registry=reg).step(0.5) is None

    def test_compiled_flops_matmul_mac_convention(self):
        import jax.numpy as jnp
        n = 64
        a = jnp.ones((n, n), jnp.float32)
        f = stepmeter.compiled_flops(lambda x, y: x @ y, a, a)
        if f is None:
            pytest.skip("backend has no cost model")
        # MAC convention: n^3 MACs (XLA reports 2*n^3 raw flops)
        assert f == pytest.approx(n ** 3, rel=0.05)
        raw = stepmeter.compiled_flops(lambda x, y: x @ y, a, a,
                                       mac_convention=False)
        assert raw == pytest.approx(2 * n ** 3, rel=0.05)

    def test_peak_flops_env_override(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "123e9")
        assert stepmeter.default_peak_flops() == 123e9

    def test_hapi_attach_step_meter_publishes_live_stats(self):
        reg = StatRegistry()
        model = _tiny_model()
        model.attach_step_meter(stepmeter.StepMeter(peak_flops=1e12,
                                                    registry=reg))
        x = paddle.to_tensor(np.random.randn(4, 6).astype("float32"))
        y = paddle.to_tensor(np.random.randn(4, 2).astype("float32"))
        for _ in range(2):
            model.train_batch(x, y)
        assert reg.get("train.flops_per_step") > 0
        assert reg.get("train.mfu") > 0
        assert reg.histogram("train.step_ms")["count"] == 2


@pytest.mark.slow
class TestMFUAgreement:
    @pytest.mark.timeout_s(900)
    def test_resnet50_flops_agree_with_bench_analytic(self):
        """Acceptance: StepMeter's cost-analysis FLOPs agree with
        bench.py's analytic ResNet-50 constant within 10% on the CPU
        proxy. Comparing FLOPs directly (rather than MFU) cancels the
        shared wall-time term, so the check is timing-noise-free."""
        from paddle_tpu.vision import models

        batch, size = 2, 96      # 96 = 224*3/7: conv-grid scaling exact
        paddle.seed(0)
        net = models.resnet50(num_classes=1000)
        reg = StatRegistry()
        model = paddle.Model(net)
        model.prepare(optim.Momentum(learning_rate=0.1, momentum=0.9,
                                     parameters=net.parameters()),
                      paddle.nn.CrossEntropyLoss())
        model.attach_step_meter(stepmeter.StepMeter(registry=reg))
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(
            rng.rand(batch, 3, size, size).astype(np.float32))
        y = paddle.to_tensor(
            rng.randint(0, 1000, (batch,)).astype(np.int64))
        model.train_batch(x, y)
        measured = reg.get("train.flops_per_step")
        assert measured > 0
        # bench.py: fwd+bwd+update ~= 3x fwd; ResNet-50 fwd @224 = 4.09
        # GFLOPs/img (MAC-as-one-FLOP), quadratic in image size
        analytic = batch * 3 * 4.09e9 * (size / 224.0) ** 2
        assert measured == pytest.approx(analytic, rel=0.10)


@pytest.mark.slow
class TestOverheadBudget:
    @pytest.mark.timeout_s(900)
    def test_disabled_tracing_overhead_within_budget(self, tmp_path):
        """Acceptance: ≤2% overhead with tracing disabled on the train
        step and the LLM decode tick. CPU timing is noisy, so take the
        best of three bench runs — a real regression fails all three."""
        from tools import bench_observability as bench
        best = None
        for _ in range(3):
            out = str(tmp_path / "bench.json")
            bench.main(["--steps", "60", "--warmup", "10", "--json", out])
            doc = json.load(open(out))
            worst = max(doc["train_step"]["overhead_pct"],
                        doc["decode_tick"]["overhead_pct"])
            best = worst if best is None else min(best, worst)
            if best <= doc["budget_pct"]:
                break
        assert best <= 2.0, f"disabled-tracing overhead {best:.2f}% > 2%"


# -- PTA005 span-fastpath lint ------------------------------------------------

class TestSpanFastpathLint:
    def _findings(self, tmp_path, rel, src):
        from tools.analyze.core import Project, run_rules
        from tools.analyze.rules import rules_by_code
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        project = Project(str(tmp_path), [rel.split("/")[0]])
        return run_rules(project, [rules_by_code()["PTA005"]])

    HOT_SRC = """
        from paddle_tpu.observability import tracer

        def hot(x):
            with tracer.span_always("op/hot"):
                return x
    """

    def test_ungated_span_in_hot_path_fires(self, tmp_path):
        found = self._findings(tmp_path, "paddle_tpu/ops/fake_op.py",
                               self.HOT_SRC)
        assert len(found) == 1
        assert "span_always" in found[0].message
        assert "zero-alloc" in found[0].message

    def test_gated_span_and_cold_path_are_clean(self, tmp_path):
        found = self._findings(tmp_path, "paddle_tpu/ops/fake_op.py", """
            from paddle_tpu.observability import span

            def hot(x):
                with span("op/hot", {"n": 1}):
                    return x
        """)
        assert found == []
        # same ungated construction OUTSIDE a hot path: not a finding
        found = self._findings(tmp_path, "paddle_tpu/io/fake_cold.py",
                               self.HOT_SRC)
        assert found == []

    def test_real_hot_paths_hold_the_invariant(self):
        """The shipped instrumentation itself obeys the rule it created:
        every hot-path module is free of ungated span construction."""
        from tools.analyze.core import Project, run_rules, filter_noqa
        from tools.analyze.rules import rules_by_code
        project = Project(REPO, ["paddle_tpu"])
        findings = run_rules(project, [rules_by_code()["PTA005"]])
        kept, _ = filter_noqa(project, findings)
        span_findings = [f for f in kept if "span" in f.message]
        assert span_findings == []
