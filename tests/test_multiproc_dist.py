"""Cross-process distributed training parity — the TestDistBase analog
(reference: python/paddle/fluid/tests/unittests/test_dist_base.py:758
_run_cluster: launch 2 trainers, pickle losses to stdout, compare with the
single-process run within delta).

Here: 2 local processes x 4 virtual CPU devices each, bootstrapped through
the PADDLE_* env contract (paddle_tpu.distributed.launch ->
init_parallel_env -> jax.distributed.initialize), training DataParallel
over the global 8-device dp mesh. Losses must match the single-process
8-device run exactly (same global batch, same seed, same collectives).
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The dist-train payload (reference analog: dist_mnist.py runTrainer).
# Single-process mode: PADDLE_TRAINERS_NUM unset -> 8 local devices.
# Multi-process mode: launched with 2 procs x 4 devices; each feeds its
# half of the SAME deterministic global batch via build_global_batch.
DIST_TRAIN = textwrap.dedent("""
    import json, os, sys
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    per_proc_devices = 8 // nprocs
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={{per_proc_devices}}")
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as optim

    dist.init_parallel_env()
    rank, world = dist.get_rank(), dist.get_world_size()
    assert jax.device_count() == 8, jax.device_count()
    dist.set_mesh(dist.build_mesh({{"dp": 8}}))

    paddle.seed(42)                      # identical init on every process
    net = paddle.nn.Sequential(
        paddle.nn.Linear(16, 32), paddle.nn.ReLU(),
        paddle.nn.Linear(32, 4))
    net = dist.DataParallel(net)
    opt = optim.Momentum(learning_rate=0.1, momentum=0.9,
                         parameters=net.parameters())
    ce = paddle.nn.CrossEntropyLoss()

    rng = np.random.RandomState(7)       # same global data everywhere
    # one fixed batch, trained on every step: descent is then a
    # deterministic property of the optimizer (the trend assertion), while
    # the per-step parity of losses still exercises the collectives
    X = rng.randn(32, 16).astype(np.float32)
    Y = rng.randint(0, 4, (32,)).astype(np.int64)
    losses = []
    for step in range(5):
        if world > 1:
            lo = rank * (32 // world)
            hi = lo + 32 // world
            xb = dist.build_global_batch(X[lo:hi])
            yb = dist.build_global_batch(Y[lo:hi])
        else:
            xb = dist.shard_batch(paddle.to_tensor(X))
            yb = dist.shard_batch(paddle.to_tensor(Y))
        loss = ce(net(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(
            loss._data if hasattr(loss, "_data") else loss)))
    print("DIST_LOSSES " + json.dumps(losses), flush=True)
""")


def _write_script(tmp_path):
    p = tmp_path / "dist_train.py"
    p.write_text(DIST_TRAIN.format(repo=REPO))
    return str(p)


def _extract(text):
    for line in text.splitlines():
        if line.startswith("DIST_LOSSES "):
            return json.loads(line[len("DIST_LOSSES "):])
    return None


@pytest.mark.slow
@pytest.mark.timeout_s(420)
def test_two_process_loss_parity(tmp_path):
    script = _write_script(tmp_path)
    # single-process reference run (8 devices, one proc)
    single = subprocess.run(
        [sys.executable, script], cwd=REPO, capture_output=True, text=True,
        timeout=180, env={**os.environ, "PYTHONPATH": REPO})
    ref = _extract(single.stdout)
    assert ref is not None, (single.stdout, single.stderr)

    # 2-process launch through the PADDLE_* contract
    log_dir = str(tmp_path / "logs")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--start_port", "12581",
         "--log_dir", log_dir, script],
        cwd=REPO, capture_output=True, text=True, timeout=300)
    logs = {}
    for rank in range(2):
        path = os.path.join(log_dir, f"workerlog.{rank}")
        logs[rank] = open(path).read() if os.path.exists(path) else "(none)"
    assert proc.returncode == 0, (proc.stderr, logs)

    for rank in range(2):
        got = _extract(logs[rank])
        assert got is not None, logs[rank]
        # reference TestDistBase uses delta=1e-3 on CPU; the computation
        # here is bit-identical module scheduling, so tighter holds
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6,
                                   err_msg=f"rank {rank} diverged: "
                                           f"{got} vs {ref}")
    # and the 5-step trend is a real training signal, not noise
    assert ref[-1] < ref[0]
