"""Collective + DataParallel tests on the 8-virtual-device CPU mesh
(conftest sets XLA_FLAGS=--xla_force_host_platform_device_count=8; SURVEY §4
"multi-process-on-one-host" tests become multi-device single-process here).

Numerics mirror the reference's collective tests
(reference: python/paddle/fluid/tests/unittests/test_collective_base.py:212
check_with_place — run a collective, compare against numpy).
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed as dist


@pytest.fixture(autouse=True)
def _mesh():
    dist.set_mesh(dist.build_mesh({"dp": 8}))
    yield
    dist.set_mesh(None)


def spmd(fn, in_specs, out_specs):
    """Run fn under shard_map on the global mesh."""
    return jax.shard_map(fn, mesh=dist.get_mesh(),
                         in_specs=in_specs, out_specs=out_specs)


class TestCollectives:
    def test_all_reduce_sum(self):
        x = np.arange(8.0, dtype=np.float32)
        out = spmd(lambda v: dist.all_reduce(v), P("dp"), P("dp"))(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), np.full(8, x.sum()))

    def test_all_reduce_ops(self):
        x = np.array([3, -1, 4, 1, -5, 9, 2, 6], np.float32)
        for op, ref in [(dist.ReduceOp.MAX, x.max()),
                        (dist.ReduceOp.MIN, x.min()),
                        (dist.ReduceOp.AVG, x.mean())]:
            out = spmd(lambda v, op=op: dist.all_reduce(v, op=op),
                       P("dp"), P("dp"))(jnp.asarray(x))
            np.testing.assert_allclose(np.asarray(out), np.full(8, ref),
                                       rtol=1e-6)

    def test_all_reduce_prod(self):
        x = np.array([1, 2, -1, 1, 1, 3, 1, 1], np.float32)
        out = spmd(lambda v: dist.all_reduce(v, op=dist.ReduceOp.PROD),
                   P("dp"), P("dp"))(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), np.full(8, x.prod()),
                                   rtol=1e-4)

    def test_all_reduce_subgroup(self):
        g = dist.new_group(ranks=[0, 1, 2, 3])
        x = np.arange(8.0, dtype=np.float32)
        out = spmd(lambda v: dist.all_reduce(v, group=g),
                   P("dp"), P("dp"))(jnp.asarray(x))
        expected = np.array([6, 6, 6, 6, 4, 5, 6, 7], np.float32)
        np.testing.assert_allclose(np.asarray(out), expected)

    def test_broadcast(self):
        x = np.arange(8.0, dtype=np.float32)
        out = spmd(lambda v: dist.broadcast(v, src=3),
                   P("dp"), P("dp"))(jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))

    def test_broadcast_subgroup(self):
        g = dist.new_group(ranks=[4, 5, 6, 7])
        x = np.arange(8.0, dtype=np.float32)
        # src is the global rank of the group's 1st member
        out = spmd(lambda v: dist.broadcast(v, src=4, group=g),
                   P("dp"), P("dp"))(jnp.asarray(x))
        expected = np.array([0, 1, 2, 3, 4, 4, 4, 4], np.float32)
        np.testing.assert_allclose(np.asarray(out), expected)

    def test_reduce_to_dst(self):
        x = np.arange(8.0, dtype=np.float32)
        out = spmd(lambda v: dist.reduce(v, dst=2),
                   P("dp"), P("dp"))(jnp.asarray(x))
        expected = x.copy()
        expected[2] = x.sum()
        np.testing.assert_allclose(np.asarray(out), expected)

    def test_all_gather(self):
        x = np.arange(16.0, dtype=np.float32).reshape(8, 2)

        def fn(v):
            return dist.all_gather(None, v)
        out = spmd(fn, P("dp", None), P(None, "dp", None))(jnp.asarray(x))
        # each rank gathers all 8 rows: [8, 1, 2] per rank
        np.testing.assert_allclose(np.asarray(out)[:, 0, :], x)

    def test_reduce_scatter(self):
        x = np.tile(np.arange(8.0, dtype=np.float32), (8, 1))  # every rank same

        def fn(v):
            return dist.reduce_scatter(None, v)
        out = spmd(fn, P("dp"), P("dp"))(jnp.asarray(x.reshape(64)))
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 8)

    def test_alltoall(self):
        # rank r holds row r: [r*8 .. r*8+7]; after alltoall rank r holds col r
        x = np.arange(64.0, dtype=np.float32).reshape(64)

        def fn(v):
            return dist.alltoall(v)
        out = spmd(fn, P("dp"), P("dp"))(jnp.asarray(x))
        expected = np.arange(64.0).reshape(8, 8).T.reshape(64)
        np.testing.assert_allclose(np.asarray(out), expected)

    def test_p2p_exchange(self):
        x = np.arange(8.0, dtype=np.float32)
        out = spmd(lambda v: dist.p2p_exchange(v, src=1, dst=5),
                   P("dp"), P("dp"))(jnp.asarray(x))
        expected = x.copy()
        expected[5] = 1.0
        np.testing.assert_allclose(np.asarray(out), expected)

    @pytest.mark.slow
    def test_all_reduce_grad(self):
        # psum is differentiable: d/dx of sum-over-ranks distributes back
        def loss(x):
            def per(v):
                return jax.lax.pmean(
                    jnp.sum(dist.all_reduce(v) ** 2), "dp")
            return spmd(per, P("dp"), P())(x)
        x = jnp.arange(8.0)
        g = jax.grad(loss)(x)
        # all_reduce output = 28 on every rank; loss = 8 * 28^2 / 8 (pmean)
        # dloss/dx_i = 2 * 28 * 8 / 8 ... verify against numeric grad
        eps = 1e-3
        num = np.zeros(8)
        for i in range(8):
            xp = np.arange(8.0); xp[i] += eps
            xm = np.arange(8.0); xm[i] -= eps
            num[i] = (float(loss(jnp.asarray(xp))) -
                      float(loss(jnp.asarray(xm)))) / (2 * eps)
        np.testing.assert_allclose(np.asarray(g), num, rtol=1e-3)

    def test_eager_world_of_one_identity(self):
        t = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out = dist.all_reduce(t)
        np.testing.assert_allclose(out.numpy(), [1.0, 2.0])
        dist.barrier()
        dist.wait(t)

    def test_get_rank_world_size(self):
        assert dist.get_rank() == 0
        assert dist.get_world_size() == 1
        g = dist.new_group(ranks=[0, 1, 2])
        assert dist.get_world_size(g) == 3


class TestTopology:
    def test_communicate_topology(self):
        topo = dist.CommunicateTopology(["data", "pipe", "model"], [2, 2, 2])
        assert topo.world_size() == 8
        assert topo.get_rank(data=1, pipe=0, model=1) == 5
        assert topo.get_coord(5) == (1, 0, 1)
        assert topo.get_axis_list("data", 0) == [0, 1, 2, 3]
        comm = topo.get_comm_list("model")
        assert [0, 1] in comm and [6, 7] in comm and len(comm) == 4

    def test_hybrid_communicate_group(self):
        hcg = dist.HybridCommunicateGroup(dp_degree=2, mp_degree=4)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_data_parallel_rank() == 0
        m = dist.get_mesh()
        assert m is not None and dict(m.shape) == {"dp": 2, "mp": 4}
        # mp-axis psum reduces within each dp slice independently
        x = np.arange(8.0, dtype=np.float32)
        out = jax.shard_map(
            lambda v: dist.all_reduce(v, group=hcg.get_model_parallel_group()),
            mesh=m, in_specs=P(("dp", "mp")), out_specs=P(("dp", "mp")))(
                jnp.asarray(x))
        expected = np.array([6, 6, 6, 6, 22, 22, 22, 22], np.float32)
        np.testing.assert_allclose(np.asarray(out), expected)


class TestDataParallel:
    def _train(self, use_dp, steps=5):
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        if use_dp:
            net = paddle.DataParallel(net)
        opt = optim.Momentum(learning_rate=0.05, momentum=0.9,
                             parameters=net.parameters())
        rng = np.random.RandomState(3)
        X = rng.randn(32, 16).astype(np.float32)
        Y = rng.randn(32, 4).astype(np.float32)
        losses = []
        for _ in range(steps):
            pred = net(paddle.to_tensor(X))
            loss = paddle.mean((pred - paddle.to_tensor(Y)) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        return losses, net

    def test_dp_matches_single_device(self):
        ref_losses, _ = self._train(use_dp=False)
        dp_losses, dp_net = self._train(use_dp=True)
        np.testing.assert_allclose(dp_losses, ref_losses, rtol=1e-5)
        assert dp_losses[-1] < dp_losses[0]

    def test_dp_input_actually_sharded(self):
        net = paddle.DataParallel(nn.Linear(4, 2))
        x = paddle.to_tensor(np.ones((16, 4), np.float32))
        captured = {}
        orig_forward = net._layers.forward

        def probe(inp):
            captured["sharding"] = inp._data.sharding
            return orig_forward(inp)
        net._layers.forward = probe
        net(x)
        spec = captured["sharding"].spec
        assert spec[0] == "dp"

    def test_dp_state_dict_roundtrip(self):
        net = paddle.DataParallel(nn.Linear(4, 2))
        sd = net.state_dict()
        assert "weight" in sd and "bias" in sd
        net.set_state_dict({k: v.numpy() * 0 for k, v in sd.items()})
        np.testing.assert_allclose(net._layers.weight.numpy(), 0)

    def test_scale_loss_identity(self):
        net = paddle.DataParallel(nn.Linear(4, 2))
        loss = paddle.to_tensor(np.float32(3.0))
        assert float(net.scale_loss(loss).numpy()) == 3.0

    def test_shard_batch_helper(self):
        t = dist.shard_batch(paddle.to_tensor(np.ones((8, 3), np.float32)))
        assert t._data.sharding.spec[0] == "dp"
        g = paddle.mean(t * 2.0)
        assert abs(float(g.numpy()) - 2.0) < 1e-6
