"""Model.train_batches (compiled K-step scan) and Model.train_loop
(coalesced flat-buffer steps) must be numerically identical to K
sequential train_batch calls — params, optimizer state, and BN running
statistics included (the state-effect threading is the risky part).

Reference analogs: fluid Executor owning the whole train loop;
operators/coalesce_tensor_op.cc + the fused optimizer family.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as optim


def _build(opt_kind):
    paddle.seed(7)
    net = paddle.nn.Sequential(
        paddle.nn.Linear(8, 16), paddle.nn.BatchNorm1D(16),
        paddle.nn.ReLU(), paddle.nn.Linear(16, 4))
    if opt_kind == "momentum":
        opt = optim.Momentum(learning_rate=1e-2, momentum=0.9,
                             parameters=net.parameters(), weight_decay=1e-3,
                             grad_clip=paddle.ClipGradByGlobalNorm(0.5))
    elif opt_kind == "adamw":
        opt = optim.AdamW(learning_rate=1e-2, parameters=net.parameters(),
                          weight_decay=0.05,
                          apply_decay_param_fun=lambda n: "weight" in n)
    else:
        opt = optim.Lamb(learning_rate=1e-2, parameters=net.parameters())
    m = paddle.Model(net)
    m.prepare(opt, paddle.nn.CrossEntropyLoss())
    return m, net


def _data():
    rng = np.random.RandomState(0)
    return (rng.randn(4, 8, 8).astype(np.float32),
            rng.randint(0, 4, (4, 8)).astype(np.int64))


def _reference_losses(opt_kind, xs, ys):
    m, net = _build(opt_kind)
    paddle.seed(123)
    losses = [m.train_batch([paddle.to_tensor(xs[k])],
                            [paddle.to_tensor(ys[k])])[0]
              for k in range(len(xs))]
    return losses, net


def _assert_state_equal(net1, net2):
    for p1, p2 in zip(net1.parameters(), net2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(),
                                   rtol=1e-4, atol=1e-5)
    s1 = {k: v.numpy() for k, v in net1.state_dict().items()}
    s2 = {k: v.numpy() for k, v in net2.state_dict().items()}
    for k in s1:
        np.testing.assert_allclose(s1[k], s2[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k)


@pytest.mark.parametrize("opt_kind", ["momentum", "adamw"])
def test_train_batches_scan_equivalence(opt_kind):
    xs, ys = _data()
    ref, net1 = _reference_losses(opt_kind, xs, ys)
    m2, net2 = _build(opt_kind)
    paddle.seed(123)
    got = m2.train_batches([paddle.to_tensor(xs)], [paddle.to_tensor(ys)])
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=1e-5)
    _assert_state_equal(net1, net2)


@pytest.mark.parametrize("opt_kind", ["momentum", "adamw"])
def test_train_loop_fused_equivalence(opt_kind):
    xs, ys = _data()
    ref, net1 = _reference_losses(opt_kind, xs, ys)
    m2, net2 = _build(opt_kind)
    paddle.seed(123)
    got = m2.train_loop([paddle.to_tensor(xs)], [paddle.to_tensor(ys)])
    assert m2._fused_loop is not None, "fused path must engage"
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=1e-5)
    _assert_state_equal(net1, net2)


def test_train_loop_falls_back_for_lamb():
    """LAMB's per-param trust ratio is not elementwise on a flat buffer;
    the loop must fall back to per-step train_batch, not silently fuse."""
    xs, ys = _data()
    ref, net1 = _reference_losses("lamb", xs, ys)
    m2, net2 = _build("lamb")
    paddle.seed(123)
    got = m2.train_loop([paddle.to_tensor(xs)], [paddle.to_tensor(ys)])
    assert m2._fused_loop is None
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=1e-5)
    _assert_state_equal(net1, net2)


def test_train_batches_rejects_metrics():
    m, _ = _build("momentum")
    m.prepare(m._optimizer, paddle.nn.CrossEntropyLoss(),
              metrics=paddle.metric.Accuracy())
    xs, ys = _data()
    with pytest.raises(ValueError):
        m.train_batches([paddle.to_tensor(xs)], [paddle.to_tensor(ys)])
    with pytest.raises(ValueError):
        m.train_loop([paddle.to_tensor(xs)], [paddle.to_tensor(ys)])


def test_multi_step_rejects_pending_accumulated_grads():
    """train_batch(update=False) leaves carried grads; the multi-step
    paths must refuse rather than silently drop them."""
    xs, ys = _data()
    m, _ = _build("momentum")
    m.train_batch([paddle.to_tensor(xs[0])], [paddle.to_tensor(ys[0])],
                  update=False)
    with pytest.raises(RuntimeError, match="pending accumulated"):
        m.train_batches([paddle.to_tensor(xs)], [paddle.to_tensor(ys)])
    with pytest.raises(RuntimeError, match="pending accumulated"):
        m.train_loop([paddle.to_tensor(xs)], [paddle.to_tensor(ys)])


def test_prepare_new_optimizer_invalidates_compiled_loops():
    """prepare(new_optimizer) must invalidate the compiled step/loop
    caches — they capture the old optimizer's update rule and write
    updated moments into the OLD optimizer's _state (round-5 advisor
    finding, hapi/model.py). Final-state comparison across the two paths
    is deliberately loose: phase-1 fused-vs-sequential fp reassociation
    noise (~1e-7, inside the pinned tolerance above) is chaotically
    amplified by Adam over the second phase, so the pin here is the
    mechanism: cleared caches, the NEW optimizer's state written with
    the NEW rule's keys, and matching per-step losses."""
    xs, ys = _data()

    def run(use_loop):
        m, net = _build("momentum")
        opt1 = m._optimizer
        paddle.seed(123)
        if use_loop:
            m.train_loop([paddle.to_tensor(xs)], [paddle.to_tensor(ys)])
        else:
            for k in range(len(xs)):
                m.train_batch([paddle.to_tensor(xs[k])],
                              [paddle.to_tensor(ys[k])])
        opt2 = optim.Adam(learning_rate=1e-2, parameters=net.parameters())
        m.prepare(opt2, paddle.nn.CrossEntropyLoss())
        assert m._fused_loop is None and m._train_step_fn is None
        paddle.seed(321)
        if use_loop:
            losses = m.train_loop([paddle.to_tensor(xs)],
                                  [paddle.to_tensor(ys)])
            assert m._fused_loop is not None, "fused path must re-engage"
        else:
            losses = [m.train_batch([paddle.to_tensor(xs[k])],
                                    [paddle.to_tensor(ys[k])])[0]
                      for k in range(len(xs))]
        # Adam (not stale Momentum) ran, and wrote into the NEW
        # optimizer's state
        assert opt2._state, "new optimizer state empty — stale cache ran"
        st = next(iter(opt2._state.values()))
        assert set(st) == {"moment1", "moment2"}, st.keys()
        assert opt2._global_step == len(xs)
        n_before = opt1._global_step
        assert n_before == len(xs)  # phase 1 only
        return losses

    ref = run(use_loop=False)
    got = run(use_loop=True)
    np.testing.assert_allclose(ref, got, rtol=1e-3, atol=1e-4)
