"""Round-3 detection family: roi ops, RPN/FPN, matching, matrix_nms
(reference: operators/detection/ roi_align_op.cc, roi_pool_op.cc,
generate_proposals_op.cc, distribute_fpn_proposals_op.cc,
collect_fpn_proposals_op.cc, bipartite_match_op.cc, target_assign_op.cc,
matrix_nms_op.cc, anchor_generator_op.cc, smooth_l1_loss_op.cc)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.ops as ops


def T(x):
    return paddle.to_tensor(np.asarray(x))


class TestRoiOps:
    def test_roi_align_uniform_feature(self):
        # constant feature map: every pooled value equals the constant
        feat = np.full((1, 2, 8, 8), 3.25, np.float32)
        rois = np.array([[1.0, 1.0, 5.0, 5.0]], np.float32)
        out = ops.roi_align(T(feat), T(rois), output_size=2,
                            spatial_scale=1.0,
                            rois_num=T(np.array([1]))).numpy()
        assert out.shape == (1, 2, 2, 2)
        np.testing.assert_allclose(out, 3.25, rtol=1e-6)

    def test_roi_align_linear_gradient_field(self):
        # f(x, y) = x: pooled bins follow bin centers
        W = 16
        feat = np.broadcast_to(np.arange(W, dtype=np.float32),
                               (1, 1, W, W)).copy()
        rois = np.array([[2.0, 2.0, 10.0, 10.0]], np.float32)
        out = ops.roi_align(T(feat), T(rois), output_size=2,
                            sampling_ratio=2, aligned=True).numpy()[0, 0]
        # bin centers along x: 2 + 8/2*0.5=4, 2+8/2*1.5=8 (minus align 0.5)
        assert out[0, 0] < out[0, 1]
        np.testing.assert_allclose(out[0], out[1], rtol=1e-5)  # y-invariant
        np.testing.assert_allclose(out[0, 1] - out[0, 0], 4.0, atol=0.1)

    def test_roi_pool_max(self):
        feat = np.zeros((1, 1, 8, 8), np.float32)
        feat[0, 0, 2, 2] = 5.0
        feat[0, 0, 5, 5] = 7.0
        rois = np.array([[0.0, 0.0, 7.0, 7.0]], np.float32)
        out = ops.roi_pool(T(feat), T(rois), output_size=2).numpy()[0, 0]
        assert out[0, 0] == 5.0 and out[1, 1] == 7.0


class TestAnchorsProposals:
    def test_anchor_generator(self):
        x = np.zeros((1, 8, 2, 2), np.float32)
        anchors, variances = ops.anchor_generator(
            T(x), anchor_sizes=[32.0], aspect_ratios=[1.0],
            variances=[0.1, 0.1, 0.2, 0.2], stride=[16, 16], offset=0.5)
        a = anchors.numpy()
        assert a.shape == (2, 2, 1, 4)
        np.testing.assert_allclose(a[0, 0, 0], [8 - 16, 8 - 16, 8 + 16,
                                                8 + 16])
        np.testing.assert_allclose(variances.numpy()[0, 0, 0],
                                   [0.1, 0.1, 0.2, 0.2])

    def test_generate_proposals_shapes_and_order(self):
        rng = np.random.RandomState(0)
        H = W = 4
        A = 3
        scores = rng.rand(1, A, H, W).astype(np.float32)
        deltas = (rng.randn(1, 4 * A, H, W) * 0.1).astype(np.float32)
        x = np.zeros((1, 8, H, W), np.float32)
        anchors, var = ops.anchor_generator(
            T(x), anchor_sizes=[16.0, 32.0, 64.0], aspect_ratios=[1.0],
            variances=[1.0, 1.0, 1.0, 1.0], stride=[8, 8])
        im_shape = np.array([[32.0, 32.0]], np.float32)
        rois, rsc, rn = ops.generate_proposals(
            T(scores), T(deltas), T(im_shape), anchors, var,
            pre_nms_top_n=48, post_nms_top_n=10, nms_thresh=0.7,
            min_size=1.0)
        assert rois.numpy().shape == (1, 10, 4)
        n = int(rn.numpy()[0])
        assert 1 <= n <= 10
        s = rsc.numpy()[0][:n]
        assert (np.diff(s) <= 1e-6).all()  # sorted desc
        b = rois.numpy()[0][:n]
        assert (b[:, 0] >= 0).all() and (b[:, 2] <= 32).all()

    def test_distribute_and_collect_fpn(self):
        rois = np.array([
            [0, 0, 10, 10],      # small -> low level
            [0, 0, 120, 120],    # medium
            [0, 0, 500, 500],    # large -> high level
        ], np.float32)
        outs, masks, restore = ops.distribute_fpn_proposals(
            T(rois), min_level=2, max_level=5, refer_level=4,
            refer_scale=224)
        masks_np = [m.numpy() for m in masks]
        lvl_of = [int(np.argmax([m[i] for m in masks_np]))
                  for i in range(3)]
        assert lvl_of[0] < lvl_of[2]
        assert sum(m.sum() for m in masks_np) == 3
        # restore index is a permutation
        assert sorted(restore.numpy().tolist()) == [0, 1, 2]

        scores = [np.array([0.9], np.float32), np.array([0.1], np.float32)]
        levels = [np.array([[0, 0, 5, 5]], np.float32),
                  np.array([[1, 1, 9, 9]], np.float32)]
        r, s = ops.collect_fpn_proposals(
            [T(levels[0]), T(levels[1])], [T(scores[0]), T(scores[1])],
            post_nms_top_n=1)
        assert s.numpy().tolist() == [np.float32(0.9)]
        np.testing.assert_allclose(r.numpy()[0], [0, 0, 5, 5])


class TestMatching:
    def test_bipartite_match_greedy(self):
        # reference test_bipartite_match_op semantics: global greedy
        dist = np.array([[0.8, 0.2, 0.1],
                         [0.9, 0.6, 0.3]], np.float32)
        match, mdist = ops.bipartite_match(T(dist))
        m = match.numpy()[0]
        # greedy: (1,0)=0.9 first, then (0,1)=0.2
        assert m[0] == 1 and m[1] == 0 and m[2] == -1
        np.testing.assert_allclose(mdist.numpy()[0][:2], [0.9, 0.2])

    def test_bipartite_match_per_prediction(self):
        dist = np.array([[0.8, 0.2, 0.75]], np.float32)
        match, mdist = ops.bipartite_match(T(dist), "per_prediction", 0.5)
        m = match.numpy()[0]
        assert m[0] == 0 and m[2] == 0 and m[1] == -1

    def test_target_assign(self):
        x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        mi = np.array([[0, -1, 1]], np.int32)
        out, w = ops.target_assign(T(x), T(mi), mismatch_value=0)
        np.testing.assert_allclose(out.numpy()[0],
                                   [[1, 2], [0, 0], [3, 4]])
        np.testing.assert_allclose(w.numpy()[0], [1, 0, 1])


class TestMatrixNMS:
    def test_overlapping_decay(self):
        # three boxes: two heavy overlaps, one isolated
        bboxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                            [50, 50, 60, 60]]], np.float32)
        scores = np.zeros((1, 2, 3), np.float32)
        scores[0, 1] = [0.9, 0.8, 0.7]
        out, counts = ops.matrix_nms(
            T(bboxes), T(scores), score_threshold=0.1, nms_top_k=3,
            keep_top_k=3, background_label=0)
        o = out.numpy()[0]
        assert int(counts.numpy()[0]) == 3  # soft NMS keeps all, decayed
        # top box undecayed at 0.9; overlapped second decayed below 0.8
        assert abs(o[0, 1] - 0.9) < 1e-6
        decayed = o[np.where(np.isclose(o[:, 2], 1.0))[0][0], 1]
        assert decayed < 0.8 * 0.7  # strong decay from high IoU
        # isolated box ~undecayed
        iso = o[np.where(np.isclose(o[:, 2], 50.0))[0][0], 1]
        assert abs(iso - 0.7) < 1e-3

    def test_smooth_l1(self):
        x = np.array([[0.0, 2.0]], np.float32)
        y = np.array([[0.5, 0.0]], np.float32)
        out = ops.smooth_l1(T(x), T(y), sigma=1.0).numpy()
        # |d|<1: 0.5*d^2 = 0.125 ; |d|>=1: |d|-0.5 = 1.5 ; summed = 1.625
        np.testing.assert_allclose(out, [[1.625]], rtol=1e-6)


class TestDeformableConv:
    def test_zero_offset_equals_conv2d(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(0)
        x = rng.randn(2, 4, 8, 8).astype(np.float32)
        w = rng.randn(6, 4, 3, 3).astype(np.float32)
        off = np.zeros((2, 18, 8, 8), np.float32)
        msk = np.ones((2, 9, 8, 8), np.float32)
        out = F.deformable_conv(T(x), T(off), T(w), mask=T(msk),
                                stride=1, padding=1).numpy()
        ref = F.conv2d(T(x), T(w), stride=1, padding=1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_integer_offset_shifts_sampling(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(1)
        off2 = np.zeros((1, 18, 4, 4), np.float32)
        off2[:, 1::2] = 1.0              # +1 in x on every tap
        x2 = rng.randn(1, 1, 6, 6).astype(np.float32)
        w2 = np.zeros((1, 1, 3, 3), np.float32)
        w2[0, 0, 1, 1] = 1.0             # pick out the center tap
        o = F.deformable_conv(T(x2), T(off2), T(w2), stride=1,
                              padding=0).numpy()
        np.testing.assert_allclose(o[0, 0], x2[0, 0, 1:5, 2:6], rtol=1e-5)

    def test_mask_modulates(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(2)
        x = rng.randn(1, 2, 5, 5).astype(np.float32)
        w = rng.randn(3, 2, 3, 3).astype(np.float32)
        off = np.zeros((1, 18, 3, 3), np.float32)
        half = np.full((1, 9, 3, 3), 0.5, np.float32)
        o_half = F.deformable_conv(T(x), T(off), T(w), mask=T(half)).numpy()
        o_full = F.deformable_conv(T(x), T(off), T(w)).numpy()
        np.testing.assert_allclose(o_half, 0.5 * o_full, rtol=1e-5)


class TestYoloEndToEnd:
    @pytest.mark.slow
    def test_loss_and_postprocess_pipeline(self):
        """YOLOv3-style train+infer slice: yolov3_loss on a head output,
        then yolo_box -> multiclass_nms postprocess (VERDICT r2 item 5
        'YOLOv3-style loss+postprocess runs')."""
        rng = np.random.RandomState(0)
        N, H = 2, 5
        anchors = [10, 13, 16, 30, 33, 23]
        mask = [0, 1, 2]
        C = 4
        A = 3
        x = paddle.to_tensor(
            (rng.randn(N, A * (5 + C), H, H) * 0.1).astype(np.float32),
            stop_gradient=False)
        gt_box = T(rng.rand(N, 6, 4).astype(np.float32) * 0.5 + 0.2)
        gt_label = T(rng.randint(0, C, (N, 6)).astype(np.int32))
        loss = ops.yolov3_loss(x, gt_box, gt_label, anchors, mask, C,
                               ignore_thresh=0.7, downsample_ratio=32)
        loss.sum().backward()
        assert np.isfinite(loss.numpy()).all()
        assert x.grad is not None and np.abs(x.grad.numpy()).sum() > 0

        img_size = T(np.array([[160, 160], [160, 160]], np.int32))
        boxes, scores = ops.yolo_box(x.detach(), img_size, anchors[:6], C,
                                     conf_thresh=0.005, downsample_ratio=32)
        out, counts = ops.multiclass_nms(
            boxes, paddle.to_tensor(
                np.transpose(scores.numpy(), (0, 2, 1))),
            score_threshold=0.01, nms_top_k=10, keep_top_k=5,
            nms_threshold=0.45, background_label=-1)
        assert out.numpy().shape == (N, 5, 6)
        assert (counts.numpy() >= 0).all()


class TestGenerateProposalsAnchorOrder:
    def test_decode_uses_matching_anchor(self):
        """Regression: scores/deltas [A,H,W] must flatten in (H,W,A) order
        to line up with anchor_generator's [H,W,A,4] layout."""
        H = W = 2
        x = np.zeros((1, 8, H, W), np.float32)
        anchors, var = ops.anchor_generator(
            T(x), anchor_sizes=[8.0, 32.0], aspect_ratios=[1.0],
            variances=[1.0, 1.0, 1.0, 1.0], stride=[8, 8])
        scores = np.zeros((1, 2, H, W), np.float32)
        scores[0, 1, 0, 0] = 0.9     # anchor a=1 (size 32) at (0,0)
        deltas = np.zeros((1, 8, H, W), np.float32)
        im_shape = np.array([[64.0, 64.0]], np.float32)
        rois, rsc, rn = ops.generate_proposals(
            T(scores), T(deltas), T(im_shape), anchors, var,
            pre_nms_top_n=8, post_nms_top_n=1, nms_thresh=0.7,
            min_size=0.0)
        # zero deltas: the roi IS the size-32 anchor centered at (4, 4),
        # clipped to the image -> [0, 0, 20, 20]
        np.testing.assert_allclose(rois.numpy()[0, 0], [0, 0, 20, 20],
                                   atol=1e-4)


class TestRoiAlignBorderClamp:
    def test_negative_coordinate_clamps_to_edge_row(self):
        """Regression: a sample point in (-1, 0) must clamp to row 0
        BEFORE the bilinear corner split (reference `if (y <= 0) y = 0`),
        not interpolate rows 0 and 1."""
        feat = np.zeros((1, 1, 2, 4), np.float32)
        feat[0, 0, 1, :] = 100.0          # row 0 is all zeros
        rois = np.array([[0.5, -1.0, 1.5, 1.0]], np.float32)
        out = ops.roi_align(T(feat), T(rois), output_size=1,
                            sampling_ratio=1, aligned=True).numpy()
        # the single sample lands at y = -0.5 -> clamped to row 0 -> 0.0
        np.testing.assert_allclose(out[0, 0, 0, 0], 0.0, atol=1e-6)


class TestBipartiteMatchMaskedEntries:
    def test_neg_inf_padding_does_not_clobber(self):
        """Regression: once all finite pairs are retired, the remaining
        greedy steps must not scatter -1 over column 0's real match."""
        dist = np.array([[0.9, -np.inf, -np.inf],
                         [-np.inf, -np.inf, -np.inf]], np.float32)
        match, mdist = ops.bipartite_match(T(dist))
        m = match.numpy()[0]
        assert m[0] == 0            # the one real pair survives
        assert m[1] == -1 and m[2] == -1
        np.testing.assert_allclose(mdist.numpy()[0][0], 0.9)


class TestRoiAlignAdaptiveApprox:
    """sampling_ratio=-1 adaptive grid (reference roi_align_op.cc:
    ceil(roi_extent/pooled_size) taps per bin) — implemented via a
    static worst-case grid with per-ROI masking, so parity must be
    exact, including on large ROIs where a fixed grid would diverge."""

    @staticmethod
    def _ref_roi_align(feat, rois, ph, pw, scale, aligned):
        # numpy transcription of roi_align_op.cc semantics with the
        # ADAPTIVE grid (sampling_ratio=-1): grid = ceil(bin extent)
        N, C, H, W = feat.shape
        roff = 0.5 if aligned else 0.0
        out = np.zeros((rois.shape[0], C, ph, pw), np.float32)

        def bilin(img, y, x):
            if y < -1 or y > H or x < -1 or x > W:
                return np.zeros(C, np.float32)
            y = min(max(y, 0.0), H - 1)
            x = min(max(x, 0.0), W - 1)
            y0, x0 = int(np.floor(y)), int(np.floor(x))
            y1, x1 = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
            wy, wx = y - y0, x - x0
            return ((1 - wy) * (1 - wx) * img[:, y0, x0]
                    + wy * (1 - wx) * img[:, y1, x0]
                    + (1 - wy) * wx * img[:, y0, x1]
                    + wy * wx * img[:, y1, x1])

        for r, (x1, y1, x2, y2) in enumerate(rois):
            x1, y1, x2, y2 = (v * scale - roff for v in (x1, y1, x2, y2))
            rw, rh = x2 - x1, y2 - y1
            if not aligned:
                rw, rh = max(rw, 1.0), max(rh, 1.0)
            bw, bh = rw / pw, rh / ph
            gy = int(np.ceil(rh / ph))
            gx = int(np.ceil(rw / pw))
            for i in range(ph):
                for j in range(pw):
                    acc = np.zeros(C, np.float32)
                    for sy in range(gy):
                        for sx in range(gx):
                            yy = y1 + bh * (i + (sy + 0.5) / gy)
                            xx = x1 + bw * (j + (sx + 0.5) / gx)
                            acc += bilin(feat[0], yy, xx)
                    out[r, :, i, j] = acc / (gy * gx)
        return out

    @pytest.mark.slow
    def test_large_roi_adaptive_grid_exact(self):
        rng = np.random.default_rng(0)
        feat = rng.standard_normal((1, 3, 32, 32)).astype(np.float32)
        # ROIs >> 2x output size: adaptive grid uses 7x7 taps/bin
        rois = np.array([[1.0, 1.0, 29.0, 29.0],
                         [0.0, 3.0, 27.0, 31.0]], np.float32)
        got = ops.roi_align(T(feat), T(rois), output_size=4,
                            sampling_ratio=-1, aligned=True,
                            rois_num=T(np.array([2]))).numpy()
        ref = self._ref_roi_align(feat, rois, 4, 4, 1.0, True)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_small_roi_exact(self):
        # ROI <= 2x output: adaptive grid is also 2x2 -> exact match
        rng = np.random.default_rng(1)
        feat = rng.standard_normal((1, 2, 16, 16)).astype(np.float32)
        rois = np.array([[2.0, 2.0, 9.0, 9.0]], np.float32)
        got = ops.roi_align(T(feat), T(rois), output_size=4,
                            sampling_ratio=-1, aligned=True).numpy()
        ref = self._ref_roi_align(feat, rois, 4, 4, 1.0, True)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
