"""for-loop / break / continue / list-append conversion under to_static
(reference: dygraph_to_static loop_transformer.py,
break_continue_transformer.py, list_transformer.py canonical patterns:
the SAME unmodified dygraph code must match eager, static-compiled)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, ops


def T(x, sg=True):
    return paddle.to_tensor(np.asarray(x, np.float32), stop_gradient=sg)


# -- canonical loop patterns (run unchanged eager AND converted) -------------

def for_static_range(x):
    s = x * 0.0
    for i in range(4):
        s = s + x * float(i + 1)
    return s


def for_tensor_bound(x, n):
    # data-dependent trip count: must lower to lax.while_loop
    s = x.sum() * 0.0
    for i in range(n):
        s = s + x.mean() + i
    return s


def for_with_break(x):
    s = x.sum() * 0.0
    for i in range(10):
        if s > 6.0:
            break
        s = s + x.mean() + 1.0
    return s


def for_with_continue(x):
    s = x.sum() * 0.0
    for i in range(6):
        if i % 2 == 0:
            continue
        s = s + x.mean() + float(i)
    return s


def while_with_break(x):
    i = paddle.to_tensor(np.float32(0.0))
    s = x.sum() * 0.0
    while i < 100.0:
        s = s + x.mean()
        if s > 3.5:
            break
        i = i + 1.0
    return s


def for_tensor_break(x, n):
    # tensor bound AND tensor break condition
    s = x.sum() * 0.0
    for i in range(n):
        if s > 2.5:
            break
        s = s + 1.0
    return s


def nested_loops_inner_break(x):
    s = x.sum() * 0.0
    for i in range(3):
        for j in range(5):
            if j >= 2:
                break               # belongs to the inner loop
            s = s + 1.0
        s = s + x.mean() * 0.0
    return s                        # 3 * 2 iterations


def list_append_stack(x):
    # list_transformer canonical pattern: append in a static-trip loop,
    # stack after (unrolls under tracing -> stacked tensor)
    outs = []
    for i in range(x.shape[0]):
        outs.append(x[i] * float(i + 1))
    return ops.stack(outs)


def iterate_tensor_rows(x):
    s = x[0] * 0.0
    for row in x:
        s = s + row * 2.0
    return s


def for_over_list(x):
    s = x * 0.0
    for c in [1.0, 2.0, 3.0]:
        s = s + x * c
    return s


CASES = [
    (for_static_range, lambda: [T(np.ones((2, 3)))]),
    (for_with_break, lambda: [T(np.ones((2, 3)))]),
    (for_with_continue, lambda: [T(np.ones((2, 3)))]),
    (while_with_break, lambda: [T(np.ones((2, 3)))]),
    (nested_loops_inner_break, lambda: [T(np.ones((2, 3)))]),
    (list_append_stack, lambda: [T(np.arange(6).reshape(3, 2))]),
    (iterate_tensor_rows, lambda: [T(np.arange(8).reshape(4, 2))]),
    (for_over_list, lambda: [T(np.ones(3))]),
]


class TestLoopEquivalence:
    @pytest.mark.parametrize("fn,mkargs", CASES,
                             ids=[c[0].__name__ for c in CASES])
    def test_eager_equals_static(self, fn, mkargs):
        eager = fn(*mkargs())
        static = jit.to_static(fn)(*mkargs())
        np.testing.assert_allclose(static.numpy(), eager.numpy(),
                                   rtol=1e-6)

    @pytest.mark.parametrize("n", [0, 3, 7])
    def test_tensor_bound_matches_python(self, n):
        x = T(np.ones((2, 2)))
        eager = for_tensor_bound(x, n)
        static = jit.to_static(for_tensor_bound)(
            x, paddle.to_tensor(np.int32(n)))
        np.testing.assert_allclose(static.numpy(), eager.numpy(),
                                   rtol=1e-6)

    @pytest.mark.parametrize("start", [0.0, 2.0])
    def test_tensor_bound_with_tensor_break(self, start):
        x = T(np.full((2, 2), start))

        def ref(n):
            s = float(4 * start) * 0.0
            for i in range(n):
                if s > 2.5:
                    break
                s = s + 1.0
            return s
        static = jit.to_static(for_tensor_break)(
            x * 0.0 + start / max(start, 1.0) * 0.0 + 0.0,
            paddle.to_tensor(np.int32(8)))
        # eager reference on the same semantics
        eager = for_tensor_break(T(np.zeros((2, 2))), 8)
        np.testing.assert_allclose(static.numpy(), eager.numpy())

    def test_grad_through_converted_for(self):
        def f(x):
            s = (x * 0.0).sum()
            for i in range(3):
                s = s + (x * float(i + 1)).sum()
            return s
        sf = jit.to_static(f)
        x = T(np.ones(4), sg=False)
        sf(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), 6.0)  # 1+2+3

    def test_grad_through_tensor_bound_for_raises(self):
        # XLA's lax.while_loop is forward-only for dynamic trip counts;
        # the error is jax's, surfaced unchanged (keep bounds static for
        # training loops — grads through static-trip fors work above)
        def f(x, n):
            s = (x * 0.0).sum()
            for i in range(n):
                s = s + (x * 2.0).sum()
            return s
        sf = jit.to_static(f)
        x = T(np.ones(4), sg=False)
        with pytest.raises(ValueError, match="while_loop|scan"):
            sf(x, paddle.to_tensor(np.int32(3))).backward()

    def test_loop_var_visible_after_loop(self):
        def f(x):
            for i in range(3):
                x = x + 1.0
            return x + float(i)    # python leaves i == 2
        np.testing.assert_allclose(
            jit.to_static(f)(T(np.zeros(2))).numpy(), 5.0)

    def test_dynamic_trip_list_append_raises_clearly(self):
        def f(x, n):
            outs = []
            for i in range(n):
                outs.append(x)
            return outs
        with pytest.raises((TypeError, Exception), match="list|Tensor"):
            jit.to_static(f)(T(np.ones(2)), paddle.to_tensor(np.int32(3)))

    def test_for_else_falls_back(self):
        # for/else is not converted; python semantics preserved eagerly
        def f(x):
            for i in range(2):
                x = x + 1.0
            else:
                x = x + 10.0
            return x
        out = f(T(np.zeros(2)))
        np.testing.assert_allclose(out.numpy(), 12.0)
        conv = jit.to_static(f)
        np.testing.assert_allclose(conv(T(np.zeros(2))).numpy(), 12.0)


class TestLoweringBails:
    """Half-lowered loops must never escape the transformer (round-5
    review findings): a bail must happen BEFORE any destructive rewrite."""

    def test_match_with_break_in_while_falls_back_cleanly(self):
        def f(x):
            i = 0
            total = x * 0.0
            while i < 10:
                total = total + float(i)
                if i >= 3:
                    break
                match int(i):
                    case 0:
                        total = total + 100.0
                    case _:
                        pass
                i = i + 1
            return total
        # eager semantics preserved (and terminates!)
        out = f(T(np.zeros(2)))
        conv = jit.to_static(f)
        np.testing.assert_allclose(conv(T(np.zeros(2))).numpy(),
                                   out.numpy())

    def test_match_in_for_body_converts_or_falls_back(self):
        def f(x):
            s = x * 0.0
            for i in range(3):
                match int(i) % 2:
                    case 0:
                        s = s + x
                    case _:
                        s = s + 2.0 * x
            return s
        out = f(T(np.ones(2)))
        np.testing.assert_allclose(
            jit.to_static(f)(T(np.ones(2))).numpy(), out.numpy())

    def test_traced_break_over_python_list_raises_not_silent(self):
        def f(x):
            total = x.sum() * 0.0
            for v in [1.0, 2.0, 3.0, 4.0]:
                total = total + v
                if total > 2.5:
                    break
            return total
        # eager: concrete flag, break works
        np.testing.assert_allclose(f(T(np.zeros(2))).numpy(), 3.0)
        # traced: must raise with guidance, never return 10.0 silently
        with pytest.raises(Exception, match="break on a traced"):
            jit.to_static(f)(T(np.zeros(2)))


class TestErrorSourceMapping:
    """Exceptions raised inside converted helpers must show the USER's
    file and line, not a synthetic <to_static ...> buffer (reference:
    dygraph_to_static/error.py, origin_info.py)."""

    def test_branch_error_points_at_user_source(self):
        import traceback

        def f(x):
            if x.mean() > 0:
                y = x * 2.0
                y = y.reshape([17, 23])      # <- raises here
            else:
                y = x
            return y
        sf = jit.to_static(f)
        try:
            sf(T(np.ones((2, 3))))
            raise AssertionError("expected reshape failure")
        except Exception as e:
            frames = traceback.extract_tb(e.__traceback__)
            ours = [fr for fr in frames if fr.filename == __file__]
            assert ours, [fr.filename for fr in frames]
            # the innermost user frame shows the real offending line text
            assert any("reshape([17, 23])" in (fr.line or "")
                       for fr in ours), [fr.line for fr in ours]

    def test_loop_body_error_points_at_user_source(self):
        import traceback

        def f(x):
            s = x * 0.0
            for i in range(3):
                s = s + x.reshape([5, 5])    # <- raises here
            return s
        sf = jit.to_static(f)
        try:
            sf(T(np.ones((2, 3))))
            raise AssertionError("expected reshape failure")
        except Exception as e:
            frames = traceback.extract_tb(e.__traceback__)
            ours = [fr for fr in frames if fr.filename == __file__]
            assert any("reshape([5, 5])" in (fr.line or "")
                       for fr in ours), [fr.line for fr in ours]


class TestLogicalOperators:
    """and/or/not on tensors under to_static (reference:
    logical_transformer.py convert_logical_and/or/not): python value
    semantics preserved for concrete operands, jnp logical ops for
    traced ones."""

    def test_tensor_and_or_in_if(self):
        def f(x, y):
            if x.sum() > 0 and y.sum() > 0:
                out = x + y
            elif x.sum() > 0 or y.sum() > 0:
                out = x - y
            else:
                out = x * 0.0
            return out
        sf = jit.to_static(f)
        for a, b in [(1.0, 1.0), (1.0, -1.0), (-1.0, 1.0), (-1.0, -1.0)]:
            xa, yb = T(np.full(3, a)), T(np.full(3, b))
            np.testing.assert_allclose(sf(xa, yb).numpy(),
                                       f(xa, yb).numpy(), rtol=1e-6)

    def test_not_on_tensor_condition(self):
        def f(x):
            if not (x.sum() > 0):
                y = x - 1.0
            else:
                y = x + 1.0
            return y
        sf = jit.to_static(f)
        np.testing.assert_allclose(sf(T(np.ones(2))).numpy(), 2.0)
        np.testing.assert_allclose(sf(T(-np.ones(2))).numpy(), -2.0)

    def test_python_value_semantics_preserved(self):
        # `a or b` returns the operand, not a bool, for concrete values
        def f(x, opt=None):
            cfg = opt or {"scale": 2.0}
            flag = opt is not None and len(opt) > 0
            if flag:
                return x * cfg["scale"] * 10.0
            return x * cfg["scale"]
        sf = jit.to_static(f)
        np.testing.assert_allclose(sf(T(np.ones(2))).numpy(), 2.0)
        np.testing.assert_allclose(
            sf(T(np.ones(2)), {"scale": 3.0}).numpy(), 30.0)


class TestAssertConversion:
    """assert in converted code (reference: assert_transformer.py):
    concrete conditions check normally (tensor conditions via .all()),
    traced ones are skipped at trace time like the reference's Assert."""

    def test_concrete_assert_fires(self):
        def f(x):
            # shapes are static under trace: this assert stays concrete
            assert x.shape[0] == 2, "batch must be 2"
            return x * 1.0
        sf = jit.to_static(f)
        np.testing.assert_allclose(sf(T(np.zeros((2, 3)))).numpy(), 0.0)
        with pytest.raises(AssertionError, match="batch must be 2"):
            sf(T(np.zeros((3, 3))))

    def test_traced_assert_skipped_not_crash(self):
        def f(x):
            assert x.sum() > -1e9          # traced: skipped, no bool()
            if x.mean() > 0:
                y = x * 2.0
            else:
                y = x
            return y
        sf = jit.to_static(f)
        np.testing.assert_allclose(sf(T(np.ones(2))).numpy(), 2.0)


class TestLogicalAssertEdgeCases:
    def test_boolop_result_is_tensor(self):
        def f(x, y):
            return (x.sum() > 0) and (y.sum() > 0)
        got = jit.to_static(f)(T(np.ones(2)), T(np.ones(2)))
        assert hasattr(got, "numpy"), type(got)   # Tensor, not raw array
        assert bool(got.numpy())

    def test_assert_msg_lazy(self):
        def f(x, err=None):
            assert err is None, f"failed: {err.code}"
            return x
        # passing assert: msg must never evaluate (err.code would raise)
        out = jit.to_static(f)(T(np.ones(2)))
        np.testing.assert_allclose(out.numpy(), 1.0)

    def test_walrus_in_boolop_not_converted(self):
        def f(x):
            if (n := x.shape[0]) and n > 1:
                return x * float(n)
            return x
        np.testing.assert_allclose(
            jit.to_static(f)(T(np.ones(3))).numpy(), 3.0)
