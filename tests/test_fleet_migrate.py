"""Zero-loss serving (docs/fault_tolerance.md "Zero-loss serving").

Five invariant families:

* **Dedup guard** — a resumed stream re-verifies every already-streamed
  token before any new token may flow: replayed tokens are swallowed
  (never re-delivered), a mismatch fails loudly with
  ``TokenStreamDivergence``, and a resume point AHEAD of the client's
  transcript raises (gap direction) instead of silently skipping.
* **Kill records** — ``BatchQueue.fail_all`` and ``Engine.kill`` return
  one snapshot record per affected request (id, phase, tokens emitted),
  and an engine with recovery armed EVACUATES in-flight requests
  (futures pending) instead of failing them.
* **Export/import** — a live paged sequence round-trips through a
  host-side ``SequenceManifest`` onto a sibling engine and the client's
  single stream iterator completes bitwise-identical to an undisturbed
  run; mismatched manifests (cold / wrong weights version / wrong model
  signature) are refused, and the ``seq_export``/``seq_import`` fault
  sites degrade exactly as documented.
* **Journal** — bounded ring semantics, finished-request pruning, and
  the ``journal_write:drop`` fault leaving STALE (but usable) records —
  the state a real crash leaves behind.
* **Fleet migration** — park and weight-roll move live streams to
  siblings instead of waiting for drain, and a hard kill replays
  journaled sequences onto survivors; in every case the client sees ONE
  uninterrupted, bitwise-correct stream.

Plus hygiene pins (PTA002 hot-prefix membership, PTA011-clean migration
plane) and the slow end-to-end chaos storm (``bench_fleet --migrate``).
"""
import os
import subprocess
import sys
import threading
import time

import pytest

import paddle_tpu as paddle
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.incubate.checkpoint import commit_checkpoint
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving.fleet import (MANIFEST_VERSION, SequenceJournal,
                                      SequenceManifest, WeightSwapper)
from paddle_tpu.serving.llm import (GenerationRequest, LLMEngine,
                                    LLMEngineConfig, SamplingParams)
from paddle_tpu.serving.queue import BatchQueue
from paddle_tpu.serving.request import EngineKilled, TokenStreamDivergence
from paddle_tpu.serving.router import Router, RouterConfig, llm_replica_factory
from paddle_tpu.utils import resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 64
PROMPT = [3, 1, 4, 1, 5, 9, 2, 6]
N_NEW = 40


def _tiny_model(seed=0):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


def _paged_cfg(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq", 64)
    kw.setdefault("max_queue", 64)
    kw.setdefault("warmup", False)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 8)
    kw.setdefault("default_max_new_tokens", N_NEW)
    return LLMEngineConfig(**kw)


def _req(prompt=PROMPT, stream=False, **kw):
    return GenerationRequest(prompt, SamplingParams(**kw), stream=stream)


def _wait_for(pred, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _ref_tokens(n=N_NEW):
    """Greedy reference stream from an engine nothing happens to."""
    with LLMEngine(_tiny_model(), _paged_cfg(),
                   registry=StatRegistry()) as eng:
        return eng.submit(PROMPT, max_new_tokens=n) \
                  .result(timeout=120)["tokens"]


@pytest.fixture
def fault_spec(monkeypatch):
    """Arm PADDLE_TPU_FAULT_SPEC for this test; disarm afterwards."""
    def arm(spec):
        monkeypatch.setenv("PADDLE_TPU_FAULT_SPEC", spec)
        resilience._reset_fault_injector_for_tests()
    yield arm
    monkeypatch.delenv("PADDLE_TPU_FAULT_SPEC", raising=False)
    resilience._reset_fault_injector_for_tests()


# -- resume-dedup guard -------------------------------------------------------

class TestDedupGuard:
    def test_replay_swallows_then_new_tokens_flow(self):
        req = _req(stream=True)
        for t in (7, 8, 9):
            assert req._emit(t)
        req.begin_resume(1)          # token 7 folded into the prompt
        assert req.prompt_len == len(PROMPT) + 1
        assert req.seq_len == len(PROMPT) + 3     # invariant under resume
        assert req._emit(8) and req._emit(9)      # verified + swallowed
        assert req.tokens == [7, 8, 9]            # nothing duplicated
        assert req._emit(4)                       # first NEW token flows
        assert req.tokens == [7, 8, 9, 4]

    def test_divergent_replay_fails_loudly(self):
        req = _req()
        for t in (7, 8, 9):
            req._emit(t)
        req.begin_resume(0)
        assert req._emit(7)
        assert req._emit(5) is False              # 8 expected
        with pytest.raises(TokenStreamDivergence):
            req.result(timeout=5)
        assert req.tokens == [7, 8, 9]            # transcript untouched

    def test_resume_ahead_of_stream_raises_gap_direction(self):
        req = _req()
        req._emit(7)
        with pytest.raises(TokenStreamDivergence):
            req.begin_resume(2)       # state AHEAD of the client's stream
        with pytest.raises(TokenStreamDivergence):
            req.begin_resume(-1)

    def test_second_resume_rebuilds_from_original_prompt(self):
        req = _req()
        for t in (7, 8):
            req._emit(t)
        req.begin_resume(2)
        assert req.prompt_len == len(PROMPT) + 2
        req.begin_resume(1)           # NOT prompt+2+1: base is original
        assert req.prompt_len == len(PROMPT) + 1
        assert req.seq_len == len(PROMPT) + 2


# -- kill snapshot records ----------------------------------------------------

class TestKillRecords:
    def test_fail_all_returns_one_record_per_request(self):
        q = BatchQueue(max_size=8)
        reqs = [_req() for _ in range(3)]
        for r in reqs:
            q.put(r, block=False)
        recs = q.fail_all(lambda: EngineKilled("gone"))
        assert [r["phase"] for r in recs] == ["queued"] * 3
        assert {r["req_id"] for r in recs} == {r.req_id for r in reqs}
        assert all(r["tokens"] == 0 for r in recs)
        for r in reqs:
            with pytest.raises(EngineKilled):
                r.result(timeout=5)

    def test_engine_kill_snapshots_queued_and_decode_phases(self):
        eng = LLMEngine(_tiny_model(), _paged_cfg(num_slots=1),
                        registry=StatRegistry())
        a = eng.submit(PROMPT, max_new_tokens=N_NEW, stream=True)
        assert _wait_for(lambda: len(a.tokens) >= 1)
        b = eng.submit(PROMPT, max_new_tokens=4)      # queued behind a
        recs = eng.kill("test kill")
        phases = {r["req_id"]: r for r in recs}
        assert phases[b.req_id]["phase"] == "queued"
        assert phases[a.req_id]["phase"] == "decode"
        assert phases[a.req_id]["tokens"] >= 1
        assert phases[a.req_id]["evacuated"] is False
        for r in (a, b):
            with pytest.raises(EngineKilled):
                r.result(timeout=5)

    def test_kill_with_recovery_evacuates_instead_of_failing(self):
        eng = LLMEngine(_tiny_model(), _paged_cfg(),
                        registry=StatRegistry())
        eng.enable_recovery()
        a = eng.submit(PROMPT, max_new_tokens=N_NEW, stream=True)
        assert _wait_for(lambda: len(a.tokens) >= 1)
        recs = eng.kill("test kill")
        dec = [r for r in recs if r["phase"] == "decode"]
        assert dec and all(r["evacuated"] for r in dec)
        # the worker detaches the requests as it stops — wait for it
        assert eng._stopped.wait(timeout=30)
        evac = eng.take_evacuated()
        assert [r.req_id for r in evac] == [a.req_id]
        assert not a.future.done()    # pending: the router owns it now
        assert eng.take_evacuated() == []   # ownership transfers once
        a.fail(EngineKilled("test cleanup"))


# -- sequence export / import -------------------------------------------------

class TestExportImport:
    def test_roundtrip_resumes_bitwise_on_sibling(self):
        ref = _ref_tokens()
        a = LLMEngine(_tiny_model(), _paged_cfg(), registry=StatRegistry())
        breg = StatRegistry()
        b = LLMEngine(_tiny_model(), _paged_cfg(), registry=breg)
        try:
            assert a.supports_migration and b.supports_migration
            req = a.submit(PROMPT, max_new_tokens=N_NEW, stream=True)
            assert _wait_for(lambda: len(req.tokens) >= 3)
            a.pause_admission()
            mans = a.export_sequences(timeout=30)
            assert len(mans) == 1
            man = mans[0]
            assert man.version == MANIFEST_VERSION and not man.cold
            assert man.n_cached_tokens == len(PROMPT) + len(man.tokens) - 1
            assert b.import_sequence(man, timeout=30)
            # the SAME iterator the client has been reading all along
            assert list(req.iter_tokens(timeout=120)) == ref
            assert req.finish_reason is not None
            stats = breg.stats()
            assert sum(v for k, v in stats.items()
                       if k.endswith(".migrated_in")) == 1
        finally:
            a.drain(timeout=30)
            b.drain(timeout=30)

    def test_import_refuses_mismatched_manifests(self):
        ref = _ref_tokens()
        a = LLMEngine(_tiny_model(), _paged_cfg(), registry=StatRegistry())
        b = LLMEngine(_tiny_model(), _paged_cfg(), registry=StatRegistry())
        try:
            req = a.submit(PROMPT, max_new_tokens=N_NEW, stream=True)
            assert _wait_for(lambda: len(req.tokens) >= 3)
            a.pause_admission()
            man = a.export_sequences(timeout=30)[0]
            cold = SequenceManifest.for_queued(_req())
            assert b.import_sequence(cold) is False    # no device state
            man.weights_version += 1                   # cross-version KV
            assert b.import_sequence(man) is False
            man.weights_version -= 1
            sig = man.sig
            man.sig = ("tampered",)                    # wrong model shape
            assert b.import_sequence(man) is False
            man.sig = sig
            # the refusals were the only obstacle: restore and resume
            assert b.import_sequence(man, timeout=30)
            assert list(req.iter_tokens(timeout=120)) == ref
        finally:
            a.drain(timeout=30)
            b.drain(timeout=30)

    def test_export_and_import_fault_sites_degrade(self, fault_spec):
        a = LLMEngine(_tiny_model(), _paged_cfg(), registry=StatRegistry())
        b = LLMEngine(_tiny_model(), _paged_cfg(), registry=StatRegistry())
        try:
            req = a.submit(PROMPT, max_new_tokens=N_NEW, stream=True)
            assert _wait_for(lambda: len(req.tokens) >= 3)
            a.pause_admission()
            fault_spec("seq_export:1:fail")
            with pytest.raises(RuntimeError):
                a.export_sequences(timeout=30)
            mans = a.export_sequences(timeout=30)      # budget spent
            assert len(mans) == 1
            fault_spec("seq_import:1:fail")
            assert b.import_sequence(mans[0]) is False  # never raises
            assert b.import_sequence(mans[0], timeout=30)
            assert list(req.iter_tokens(timeout=120))
        finally:
            a.drain(timeout=30)
            b.drain(timeout=30)


# -- sequence journal ---------------------------------------------------------

class TestJournal:
    def _mk(self, **kw):
        kw.setdefault("capacity", 4)
        kw.setdefault("flush_interval", 999.0)   # manual flushes only
        kw.setdefault("registry", StatRegistry())
        return SequenceJournal(**kw)

    def test_ring_is_bounded_and_lookup_sees_newest(self):
        j = self._mk()
        try:
            reqs = [_req() for _ in range(6)]
            for r in reqs:
                r._emit(5)
            j.note(reqs)
            j.flush_pending()
            assert len(j) == 4                       # capacity, not 6
            assert j.lookup(reqs[0].req_id) is None  # oldest evicted
            rec = j.lookup(reqs[-1].req_id)
            assert rec is not None and rec.tokens == [5]
        finally:
            j.close()

    def test_finished_requests_are_pruned(self):
        j = self._mk()
        try:
            r = _req()
            r._emit(3)
            j.note([r])
            j.flush_pending()
            assert j.lookup(r.req_id) is not None
            r._finish("stop")
            j.note([r])
            j.flush_pending()
            assert j.lookup(r.req_id) is None       # nothing to recover
            assert j.snapshot() == []
        finally:
            j.close()

    def test_dropped_write_leaves_stale_records(self, fault_spec):
        j = self._mk()
        try:
            r = _req()
            r._emit(3)
            j.note([r])
            j.flush_pending()
            fault_spec("journal_write:1:drop")
            r._emit(4)
            j.note([r])
            j.flush_pending()                        # lost write
            assert j.lookup(r.req_id).tokens == [3]  # stale, still usable
            j.note([r])
            j.flush_pending()                        # budget spent
            assert j.lookup(r.req_id).tokens == [3, 4]
        finally:
            j.close()

    def test_failed_write_counts_errors(self, fault_spec):
        j = self._mk()
        try:
            fault_spec("journal_write:1:fail")
            r = _req()
            r._emit(3)
            j.note([r])
            j.flush_pending()
            assert j.write_errors == 1
            assert j.lookup(r.req_id) is None
        finally:
            j.close()


# -- fleet-level migration ----------------------------------------------------

def _mk_paged_router(n=2, **rcfg):
    rcfg.setdefault("health_interval", 0.05)
    reg = StatRegistry()
    router = Router(
        llm_replica_factory(lambda r: _tiny_model(), _paged_cfg()),
        RouterConfig(num_replicas=n, kind="llm", **rcfg),
        registry=reg)
    return router, reg


class TestFleetMigration:
    def test_park_migrates_live_stream_to_sibling(self):
        ref = _ref_tokens()
        router, reg = _mk_paged_router(2)
        try:
            assert router.migrator is not None     # armed for llm fleets
            req = router.submit(PROMPT, max_new_tokens=N_NEW, stream=True)
            assert _wait_for(lambda: len(req.tokens) >= 3)
            donor = max(router.replicas, key=lambda r: r.outstanding)
            assert router.park(donor.replica_id)
            # the client's ONE iterator rides through the park untouched
            assert list(req.iter_tokens(timeout=120)) == ref
            stats = reg.stats()
            assert stats.get("fleet.migrate.sequences_exported", 0) >= 1
            adopted = (stats.get("fleet.migrate.sequences_imported", 0)
                       + stats.get("fleet.migrate.sequences_replayed", 0))
            assert adopted >= 1
            assert stats.get("fleet.migrate.sequences_failed", 0) == 0
        finally:
            router.drain(timeout=60)

    def test_kill_replays_journaled_stream_on_survivor(self):
        ref = _ref_tokens()
        router, reg = _mk_paged_router(2)
        try:
            req = router.submit(PROMPT, max_new_tokens=N_NEW, stream=True)
            assert _wait_for(lambda: len(req.tokens) >= 3)
            victim = max(router.replicas, key=lambda r: r.outstanding)
            victim.kill("chaos: test kill")
            # journal replay re-prefills on a survivor; the dedup guard
            # swallows the already-streamed prefix — bitwise, no dups
            assert list(req.iter_tokens(timeout=120)) == ref
            assert _wait_for(lambda: reg.stats().get(
                "fleet.migrate.sequences_recovered", 0) >= 1)
            assert sum(v for k, v in reg.stats().items()
                       if k.endswith(".stream_divergence")) == 0
        finally:
            router.drain(timeout=60)

    def test_weight_roll_migrates_instead_of_draining(self, tmp_path):
        ref = _ref_tokens()
        router, reg = _mk_paged_router(2)
        # sustained load (a one-shot stream finishes during checkpoint
        # load / the first replica's probe): pumps keep streams in
        # flight until the whole roll has completed, so migrate-out is
        # guaranteed to find live sequences on each replica it pauses
        stop = threading.Event()
        done, rejected = [], []

        def pump():
            while not stop.is_set():
                try:
                    r = router.submit(PROMPT, max_new_tokens=N_NEW,
                                      stream=True)
                    done.append(list(r.iter_tokens(timeout=120)))
                except Exception as e:  # retryable paused/draining windows
                    rejected.append(repr(e))
                    time.sleep(0.02)
        pumps = [threading.Thread(target=pump, daemon=True)
                 for _ in range(4)]
        try:
            ckpt = str(tmp_path / "ckpt-step1")
            commit_checkpoint({"model": _tiny_model().state_dict()},
                              ckpt, healthy=True, step=1)
            swapper = WeightSwapper(router, reg, quiesce_timeout=60.0,
                                    probe_timeout=60.0)
            for t in pumps:
                t.start()
            assert _wait_for(lambda: sum(
                r.outstanding for r in router.replicas) >= 2)
            report = swapper.roll(ckpt)
            stop.set()
            for t in pumps:
                t.join(timeout=150)
            assert not report.get("aborted")
            assert sorted(report["swapped"]) == [0, 1]
            assert sum(report.get("migrated", {}).values()) >= 1
            # identical weights either side of the roll: still bitwise
            assert done and all(t == ref for t in done)
            assert reg.stats().get(
                "fleet.migrate.sequences_exported", 0) >= 1
        finally:
            stop.set()
            router.drain(timeout=60)


# -- hygiene pins -------------------------------------------------------------

def test_migrate_module_is_pta002_hot():
    from tools.analyze.rules.pta002_host_sync import HOT_PREFIXES
    assert "paddle_tpu/serving/fleet/migrate.py" in HOT_PREFIXES
    assert "paddle_tpu/serving/fleet/" in HOT_PREFIXES


def test_pta011_clean_on_migration_plane():
    # the export path must never gate a collective on replica rank —
    # PTA011 over the whole migration plane stays finding-free
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--baseline", "none",
         "--rule", "PTA011", "--json",
         "paddle_tpu/serving/fleet", "paddle_tpu/serving/llm/paged"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- slow end-to-end ----------------------------------------------------------

@pytest.mark.slow
def test_zero_loss_storm_end_to_end():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.bench_fleet", "--migrate",
         "--check", "--replicas", "2", "--streams", "12"],
        cwd=REPO, capture_output=True, text=True, env=env, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
