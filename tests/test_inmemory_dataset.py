"""InMemoryDataset / QueueDataset — the reference's industrial bulk
pipeline (fleet/dataset/dataset.py:253 over data_set.h:43): file-sharded
ingestion, local + global shuffle, batch iteration; the 2-process global
shuffle runs through the launcher and must partition the instance set.
"""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu.distributed as dist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_files(tmp_path, n_files=4, rows_per=8, width=3):
    files = []
    v = 0
    for i in range(n_files):
        p = tmp_path / f"part-{i:03d}.txt"
        with open(p, "w") as f:
            for _ in range(rows_per):
                f.write(" ".join(str(v * width + j) for j in range(width))
                        + "\n")
                v += 1
        files.append(str(p))
    return files


def test_load_and_batches(tmp_path):
    files = _write_files(tmp_path)
    ds = dist.InMemoryDataset()
    ds.init(batch_size=5, thread_num=2)
    ds.set_filelist(files)
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 32
    batches = list(ds.batch_iterator())
    assert [b.shape for b in batches] == [(5, 3)] * 6 + [(2, 3)]
    # all 32 rows present exactly once
    allrows = np.concatenate(batches)
    assert sorted(allrows[:, 0].tolist()) == [float(3 * i) for i in range(32)]
    # drop_last
    ds.init(batch_size=5, thread_num=2, drop_last=True)
    assert len(list(ds.batch_iterator())) == 6 and len(ds) == 6


def test_local_shuffle_deterministic(tmp_path):
    files = _write_files(tmp_path)
    ds = dist.InMemoryDataset()
    ds.init(batch_size=32)
    ds.set_filelist(files)
    ds.load_into_memory()
    before = np.concatenate(list(ds.batch_iterator()))
    ds.local_shuffle(seed=7)
    after = np.concatenate(list(ds.batch_iterator()))
    assert not np.array_equal(before, after)
    np.testing.assert_allclose(np.sort(before[:, 0]), np.sort(after[:, 0]))
    # single-process global_shuffle degenerates to local
    ds.global_shuffle(seed=7)
    assert ds.get_shuffle_data_size() == 32


def test_custom_parse_fn_tuple_samples(tmp_path):
    p = tmp_path / "f.txt"
    p.write_text("1 2 3 0\n4 5 6 1\n7 8 9 0\n")
    ds = dist.InMemoryDataset()
    ds.init(batch_size=2, parse_fn=lambda line: (
        np.asarray([float(v) for v in line.split()[:-1]], np.float32),
        np.int64(line.split()[-1])))
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    x, y = next(iter(ds))
    assert x.shape == (2, 3) and y.shape == (2,)
    np.testing.assert_array_equal(y, [0, 1])


def test_queue_dataset_streams(tmp_path):
    files = _write_files(tmp_path, n_files=2, rows_per=5)
    ds = dist.QueueDataset()
    ds.init(batch_size=4)
    ds.set_filelist(files)
    got = np.concatenate(list(ds))
    assert got.shape == (10, 3)
    with pytest.raises(RuntimeError):
        ds.local_shuffle()


GLOBAL_SHUFFLE_SCRIPT = textwrap.dedent("""
    import json, os, sys
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import numpy as np
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    files = json.loads(os.environ["DS_FILES"])
    ds = dist.InMemoryDataset()
    ds.init(batch_size=4, thread_num=2)
    ds.set_filelist(files)
    ds.load_into_memory()
    total_before = ds.get_memory_data_size()
    ds.global_shuffle(seed=3)
    mine = sorted(int(b[0]) // 3 for b in ds._samples)
    print("DS_RESULT " + json.dumps({{
        "rank": dist.get_rank(), "total": total_before, "mine": mine,
        "post_total": ds.get_shuffle_data_size()}}), flush=True)
""")


@pytest.mark.slow
@pytest.mark.timeout_s(300)
def test_global_shuffle_partitions_two_procs(tmp_path):
    files = _write_files(tmp_path, n_files=4, rows_per=8)
    script = tmp_path / "gs.py"
    script.write_text(GLOBAL_SHUFFLE_SCRIPT.format(repo=REPO))
    log_dir = str(tmp_path / "logs")
    env = {**os.environ, "DS_FILES": json.dumps(files)}
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--start_port", "12641",
         "--log_dir", log_dir, str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=240, env=env)
    results = {}
    for rank in range(2):
        with open(os.path.join(log_dir, f"workerlog.{rank}")) as f:
            for line in f:
                if line.startswith("DS_RESULT "):
                    r = json.loads(line[len("DS_RESULT "):])
                    results[r["rank"]] = r
    assert proc.returncode == 0, (proc.stderr, results)
    assert set(results) == {0, 1}
    # file-level sharding before shuffle: each proc saw 16 of 32; totals
    # are global
    assert results[0]["total"] == results[1]["total"] == 32
    assert results[0]["post_total"] == 32
    # after global shuffle: a disjoint partition of all 32 instances
    m0, m1 = set(results[0]["mine"]), set(results[1]["mine"])
    assert m0.isdisjoint(m1)
    assert m0 | m1 == set(range(32))
    # hash-routing actually crossed processes (not identity)
    assert m0 != set(range(16))
