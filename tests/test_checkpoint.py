"""Sharded / async / auto checkpointing + FS facade
(reference: incubate/checkpoint/auto_checkpoint.py:71 TrainEpochRange,
fleet/utils/fs.py LocalFS:115/HDFSClient:419)."""
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed as dist
from paddle_tpu.incubate.checkpoint import (save_sharded, load_sharded,
                                            AsyncSaver, TrainEpochRange)
from paddle_tpu.distributed.fleet.fs import (LocalFS, HDFSClient,
                                             ExecuteError)


class TestShardedCheckpoint:
    def test_roundtrip_sharded_array(self, tmp_path):
        mesh = dist.build_mesh({"dp": 8})
        x = np.arange(64, dtype=np.float32).reshape(8, 8)
        arr = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
        state = {"w": arr, "nested": {"b": jnp.ones(3)}, "step": 7}
        save_sharded(state, str(tmp_path / "ck"))
        out = load_sharded(str(tmp_path / "ck"), mesh=mesh)
        np.testing.assert_allclose(out["w"].numpy(), x)
        # resharded onto the mesh with the recorded spec
        assert "dp" in str(out["w"]._data.sharding.spec)
        np.testing.assert_allclose(out["nested"]["b"].numpy(), np.ones(3))
        assert out["step"] == 7

    def test_reshard_on_load_to_different_mesh(self, tmp_path):
        mesh8 = dist.build_mesh({"dp": 8})
        x = np.arange(32, dtype=np.float32).reshape(8, 4)
        arr = jax.device_put(x, NamedSharding(mesh8, P("dp", None)))
        save_sharded({"w": arr}, str(tmp_path / "ck"))
        # new topology: 4-device mesh with a different axis name
        mesh4 = dist.build_mesh({"mp": 4}, jax.devices()[:4])
        out = load_sharded(str(tmp_path / "ck"), mesh=mesh4)
        np.testing.assert_allclose(out["w"].numpy(), x)  # replicated now

    def test_async_saver(self, tmp_path):
        s = AsyncSaver()
        state = {"a": jnp.arange(10.0)}
        s.save(state, str(tmp_path / "ck"))
        s.wait()
        out = load_sharded(str(tmp_path / "ck"))
        np.testing.assert_allclose(out["a"].numpy(), np.arange(10.0))


def _make_model_and_data():
    paddle.seed(7)
    net = nn.Linear(4, 2)
    opt = optim.AdamW(learning_rate=1e-2, parameters=net.parameters())
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    Y = rng.randn(16, 2).astype(np.float32)
    return net, opt, X, Y


def _train_epoch(net, opt, X, Y):
    x, y = paddle.to_tensor(X), paddle.to_tensor(Y)
    loss = paddle.mean((net(x) - y) ** 2)
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.numpy())


class TestAutoCheckpoint:
    def test_kill_and_resume_identical_losses(self, tmp_path):
        ckpt = str(tmp_path / "auto")
        # uninterrupted run: 6 epochs
        net, opt, X, Y = _make_model_and_data()
        full_losses = [_train_epoch(net, opt, X, Y) for _ in range(6)]

        # interrupted run: 3 epochs, then "kill"
        net1, opt1, X, Y = _make_model_and_data()
        r1 = TrainEpochRange(6, "job0", model=net1, optimizer=opt1,
                             checkpoint_path=ckpt)
        losses_a = []
        for epoch in r1:
            losses_a.append(_train_epoch(net1, opt1, X, Y))
            if epoch == 2:
                break  # simulated failure AFTER epoch 2 was checkpointed
        r1.save(2)

        # restart: fresh objects, same job name -> resumes at epoch 3
        net2, opt2, X, Y = _make_model_and_data()
        r2 = TrainEpochRange(6, "job0", model=net2, optimizer=opt2,
                             checkpoint_path=ckpt)
        assert r2.restored_epoch == 2
        losses_b = []
        for epoch in r2:
            losses_b.append(_train_epoch(net2, opt2, X, Y))
        resumed = losses_a[:3] + losses_b
        np.testing.assert_allclose(resumed, full_losses, rtol=1e-5)

    def test_sharded_params_roundtrip_on_mesh(self, tmp_path):
        mesh = dist.build_mesh({"dp": 8})
        dist.set_mesh(mesh)
        try:
            net, opt, X, Y = _make_model_and_data()
            dist.shard_tensor(net.weight, P(None, None), mesh)
            _train_epoch(net, opt, X, Y)
            state = {"model": net.state_dict(),
                     "optimizer": opt.state_dict()}
            save_sharded(state, str(tmp_path / "ck"))
            out = load_sharded(str(tmp_path / "ck"), mesh=mesh)
            np.testing.assert_allclose(
                out["model"]["weight"].numpy(), net.weight.numpy())
            got = {k for k in out["optimizer"]}
            assert any(k.startswith("param_0.") for k in got)
        finally:
            dist.set_mesh(None)


class TestFSFacade:
    def test_localfs(self, tmp_path):
        fs = LocalFS()
        d = str(tmp_path / "a" / "b")
        fs.mkdirs(d)
        assert fs.is_dir(d) and fs.is_exist(d)
        f = os.path.join(d, "x.txt")
        fs.touch(f)
        assert fs.is_file(f)
        dirs, files = fs.ls_dir(d)
        assert files == ["x.txt"] and dirs == []
        fs.mv(f, os.path.join(d, "y.txt"))
        assert fs.is_file(os.path.join(d, "y.txt"))
        assert not fs.need_upload_download()
        fs.upload(os.path.join(d, "y.txt"), str(tmp_path / "z.txt"))
        assert fs.is_file(str(tmp_path / "z.txt"))
        assert fs.list_dirs(str(tmp_path)) == ["a"]
        fs.delete(d)
        assert not fs.is_exist(d)

    def test_hdfs_requires_binary(self):
        if __import__("shutil").which("hadoop"):
            pytest.skip("hadoop present")
        with pytest.raises(ExecuteError):
            HDFSClient()
