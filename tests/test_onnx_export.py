"""onnx.export produces a REAL .onnx (round-5: the repo's last stub is
gone). Validation without onnxruntime in the image: a minimal in-repo
protobuf reader parses the file back and a numpy interpreter replays the
graph; outputs must equal the framework's own forward."""
import struct

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.static import InputSpec


# -- minimal protobuf reader (wire format) -----------------------------------

def _read_varint(b, i):
    out = shift = 0
    while True:
        x = b[i]
        i += 1
        out |= (x & 0x7F) << shift
        if not x & 0x80:
            return out, i
        shift += 7


def _fields(buf):
    """Yield (field_no, wire_type, value) over a message buffer."""
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        else:
            raise ValueError(f"wire type {wire}")
        yield field, wire, v


def _parse_tensor(buf):
    dims, dtype, name, raw = [], None, "", b""
    for f, w, v in _fields(buf):
        if f == 1:
            dims.append(v)
        elif f == 2:
            dtype = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
    np_dt = {1: np.float32, 6: np.int32, 7: np.int64, 9: np.bool_,
             11: np.float64}[dtype]
    return name, np.frombuffer(raw, np_dt).reshape(dims)


def _parse_node(buf):
    ins, outs, op, attrs = [], [], "", {}
    for f, w, v in _fields(buf):
        if f == 1:
            ins.append(v.decode())
        elif f == 2:
            outs.append(v.decode())
        elif f == 4:
            op = v.decode()
        elif f == 5:
            nm, ints, i_val, f_val, typ = "", [], None, None, None
            for ff, ww, vv in _fields(v):
                if ff == 1:
                    nm = vv.decode()
                elif ff == 8:
                    ints.append(vv)
                elif ff == 3:
                    i_val = vv
                elif ff == 2:
                    f_val = vv
                elif ff == 20:
                    typ = vv
            attrs[nm] = (ints if typ == 7 else
                         i_val if typ == 2 else f_val)
    return op, ins, outs, attrs


def load_onnx(path):
    model = open(path, "rb").read()
    graph = None
    opset = None
    for f, w, v in _fields(model):
        if f == 7:
            graph = v
        elif f == 8:
            for ff, ww, vv in _fields(v):
                if ff == 2:
                    opset = vv
    assert graph is not None and opset == 13
    nodes, inits, inputs, outputs = [], {}, [], []
    for f, w, v in _fields(graph):
        if f == 1:
            nodes.append(_parse_node(v))
        elif f == 5:
            nm, arr = _parse_tensor(v)
            inits[nm] = arr
        elif f == 11:
            for ff, _, vv in _fields(v):
                if ff == 1:
                    inputs.append(vv.decode())
        elif f == 12:
            for ff, _, vv in _fields(v):
                if ff == 1:
                    outputs.append(vv.decode())
    return nodes, inits, inputs, outputs


# -- numpy interpreter --------------------------------------------------------

def run_onnx(path, feeds):
    nodes, env, inputs, outputs = load_onnx(path)
    env = dict(env)
    for nm, a in zip(inputs, feeds):
        env[nm] = np.asarray(a)
    for op, ins, outs, at in nodes:
        a = [env[i] for i in ins]
        if op == "MatMul":
            r = a[0] @ a[1]
        elif op == "Add":
            r = a[0] + a[1]
        elif op == "Sub":
            r = a[0] - a[1]
        elif op == "Mul":
            r = a[0] * a[1]
        elif op == "Div":
            r = a[0] / a[1]
        elif op == "Max":
            r = np.maximum(a[0], a[1])
        elif op == "Min":
            r = np.minimum(a[0], a[1])
        elif op == "Pow":
            r = a[0] ** a[1]
        elif op == "Neg":
            r = -a[0]
        elif op == "Exp":
            r = np.exp(a[0])
        elif op == "Log":
            r = np.log(a[0])
        elif op == "Sqrt":
            r = np.sqrt(a[0])
        elif op == "Reciprocal":
            r = 1.0 / a[0]
        elif op == "Tanh":
            r = np.tanh(a[0])
        elif op == "Sigmoid":
            r = 1 / (1 + np.exp(-a[0]))
        elif op == "Abs":
            r = np.abs(a[0])
        elif op == "Identity":
            r = a[0]
        elif op == "Cast":
            np_dt = {1: np.float32, 6: np.int32, 7: np.int64,
                     9: np.bool_}[at["to"]]
            r = a[0].astype(np_dt)
        elif op == "Reshape":
            r = a[0].reshape([int(d) for d in a[1]])
        elif op == "Transpose":
            r = np.transpose(a[0], at["perm"])
        elif op == "Gather":
            r = np.take(a[0], a[1].astype(np.int64), axis=at.get("axis", 0))
        elif op == "Clip":
            r = np.clip(a[0], a[1], a[2])
        elif op == "Expand":
            r = np.broadcast_to(a[0], [int(d) for d in a[1]]).copy()
        elif op == "Concat":
            r = np.concatenate(a, axis=at["axis"])
        elif op == "Slice":
            starts, ends, axes, steps = (a[1], a[2], a[3], a[4])
            sl = [slice(None)] * a[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                sl[int(ax)] = slice(int(s), int(e), int(st))
            r = a[0][tuple(sl)]
        elif op == "ReduceSum":
            r = a[0].sum(axis=tuple(int(d) for d in a[1]),
                         keepdims=bool(at.get("keepdims", 1)))
        elif op in ("ReduceMax", "ReduceMin"):
            fn = np.max if op == "ReduceMax" else np.min
            r = fn(a[0], axis=tuple(at["axes"]),
                   keepdims=bool(at.get("keepdims", 1)))
        elif op == "Where":
            r = np.where(a[0], a[1], a[2])
        elif op == "Greater":
            r = a[0] > a[1]
        elif op == "Less":
            r = a[0] < a[1]
        elif op == "GreaterOrEqual":
            r = a[0] >= a[1]
        elif op == "LessOrEqual":
            r = a[0] <= a[1]
        elif op == "Equal":
            r = a[0] == a[1]
        elif op == "Not":
            r = ~a[0]
        elif op == "Erf":
            import math
            r = np.vectorize(math.erf)(a[0]).astype(np.float32)
        elif op == "Conv":
            r = _np_conv(a[0], a[1], a[2] if len(a) > 2 else None, at)
        elif op == "MaxPool":
            r = _np_pool(a[0], at, np.max, -np.inf)
        elif op == "AveragePool":
            r = _np_pool(a[0], at, np.mean, 0.0)
        else:
            raise NotImplementedError(f"replayer: {op}")
        env[outs[0]] = r
    return [env[o] for o in outputs]


def _np_conv(x, w, b, at):
    import torch
    r = torch.nn.functional.conv2d(
        torch.from_numpy(np.ascontiguousarray(x)),
        torch.from_numpy(np.ascontiguousarray(w)),
        torch.from_numpy(np.ascontiguousarray(b)) if b is not None
        else None,
        stride=tuple(at["strides"]),
        padding=tuple(at["pads"][:2]),
        dilation=tuple(at.get("dilations", [1, 1])),
        groups=at.get("group", 1)).numpy()
    return r


def _np_pool(x, at, fn, pad_val):
    import torch
    t = torch.from_numpy(np.ascontiguousarray(x))
    k, s = tuple(at["kernel_shape"]), tuple(at["strides"])
    pads = tuple(at["pads"][:2])
    if fn is np.max:
        return torch.nn.functional.max_pool2d(t, k, s, pads).numpy()
    return torch.nn.functional.avg_pool2d(t, k, s, pads).numpy()


# -- tests --------------------------------------------------------------------

def test_mlp_roundtrip(tmp_path):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                        nn.Softmax())
    x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
    path = str(tmp_path / "mlp.onnx")
    paddle.onnx.export(net, path, input_spec=[InputSpec([3, 8], "float32",
                                                        "x")])
    got = run_onnx(path, [x])[0]
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_lenet_conv_roundtrip(tmp_path):
    paddle.seed(1)
    from paddle_tpu.vision.models import LeNet
    net = LeNet()
    net.eval()
    x = np.random.RandomState(1).rand(2, 1, 28, 28).astype(np.float32)
    path = str(tmp_path / "lenet.onnx")
    paddle.onnx.export(net, path,
                       input_spec=[InputSpec([2, 1, 28, 28], "float32",
                                             "img")])
    got = run_onnx(path, [x])[0]
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


def test_unsupported_primitive_raises_clearly(tmp_path):
    class Fancy(nn.Layer):
        def forward(self, x):
            from paddle_tpu import ops
            return ops.cumsum(x, axis=0)
    with pytest.raises(NotImplementedError, match="primitive"):
        paddle.onnx.export(Fancy(), str(tmp_path / "f.onnx"),
                           input_spec=[InputSpec([3, 4], "float32", "x")])


def test_non_onnx_path_still_writes_stablehlo(tmp_path):
    paddle.seed(2)
    net = nn.Sequential(nn.Linear(4, 2))
    prefix = str(tmp_path / "model")
    paddle.onnx.export(net, prefix,
                       input_spec=[InputSpec([1, 4], "float32", "x")])
    import os
    assert os.path.exists(prefix + ".pdmodel")


@pytest.mark.slow
def test_resnet18_roundtrip(tmp_path):
    from paddle_tpu.vision.models import resnet18
    paddle.seed(0)
    net = resnet18(num_classes=10)
    net.eval()
    x = np.random.RandomState(0).rand(1, 3, 32, 32).astype(np.float32)
    path = str(tmp_path / "r18.onnx")
    paddle.onnx.export(net, path,
                       input_spec=[InputSpec([1, 3, 32, 32], "float32",
                                             "x")])
    got = run_onnx(path, [x])[0]
    ref = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_gpt_transformer_roundtrip(tmp_path):
    """Transformers export too: embedding gather, batched attention
    matmuls (general dot_general), gelu's erfc, causal-mask select."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=4, max_position_embeddings=16,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    attn_impl="dense")
    net = GPTForCausalLM(cfg)
    net.eval()
    path = str(tmp_path / "gpt.onnx")
    paddle.onnx.export(net, path,
                       input_spec=[InputSpec([1, 8], "int32", "ids")])
    ids = np.random.RandomState(0).randint(0, 64, (1, 8)).astype(np.int32)
    got = run_onnx(path, [ids])[0]
    ref = net(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
