"""Pin the driver contracts: entry() compile-check + dryrun_multichip(8)
(VERDICT round-1 item 2: these must exist and pass)."""
import sys
import numpy as np
import jax
import pytest


sys.path.insert(0, "/root/repo")


def test_entry_jittable():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (4, 32, 256)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_dryrun_multichip_8():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)  # raises on failure


def test_models_import():
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM, BertConfig,
                                   BertModel)
    from paddle_tpu.models.gpt import tp_partition_specs
    m = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                                 num_heads=2, max_position_embeddings=16))
    specs = tp_partition_specs(m)
    # the Megatron plan must mark col/row splits
    col = [k for k, v in specs.items() if v == (None, "mp")]
    row = [k for k, v in specs.items() if v == ("mp", None)]
    assert any("q_proj.weight" in k for k in col)
    assert any("linear1.weight" in k for k in col)
    assert any("out_proj.weight" in k for k in row)
    assert any("word_embeddings.weight" in k for k in row)
