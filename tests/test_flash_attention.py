"""Flash-attention Pallas kernel (ops/pallas_attention.py): exact
equivalence with dense attention — forward and all three gradients,
causal and full, including non-block-multiple sequence lengths (tail
padding) and cross-attention (kv length != q length). Runs in interpret
mode on CPU; the TPU-compiled path is numerics-checked by the bench
probes (docs/perf_notes.md round 4)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _dense(q, k, v, causal, scale):
    qd = jnp.moveaxis(q, 2, 1)
    kd = jnp.moveaxis(k, 2, 1)
    vd = jnp.moveaxis(v, 2, 1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qd, kd) * scale
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(Sq)[:, None] >= jnp.arange(Sk)[None, :]
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.moveaxis(jnp.einsum("bhqk,bhkd->bhqd", p, vd), 1, 2)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("S", [128, 200, 100])
def test_forward_matches_dense(causal, S):
    rng = np.random.RandomState(0)
    B, H, D = 2, 4, 64
    q = rng.randn(B, S, H, D).astype(np.float32) * 0.5
    k = rng.randn(B, S, H, D).astype(np.float32) * 0.5
    v = rng.randn(B, S, H, D).astype(np.float32)
    out, _ = F.flash_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                               paddle.to_tensor(v), causal=causal)
    out = out.numpy()
    ref = np.asarray(_dense(q, k, v, causal, 1 / np.sqrt(D)))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_dense(causal):
    rng = np.random.RandomState(1)
    B, S, H, D = 1, 96, 2, 32
    q = rng.randn(B, S, H, D).astype(np.float32) * 0.5
    k = rng.randn(B, S, H, D).astype(np.float32) * 0.5
    v = rng.randn(B, S, H, D).astype(np.float32)
    qt, kt, vt = map(paddle.to_tensor, (q, k, v))
    for t in (qt, kt, vt):
        t.stop_gradient = False
    out, _ = F.flash_attention(qt, kt, vt, causal=causal)
    (out * out).sum().backward()

    def loss(q, k, v):
        o = _dense(q, k, v, causal, 1 / np.sqrt(D))
        return jnp.sum(o * o)
    gq, gk, gv = jax.grad(loss, (0, 1, 2))(q, k, v)
    for got, want in [(qt.grad, gq), (kt.grad, gk), (vt.grad, gv)]:
        got, want = np.asarray(got.numpy()), np.asarray(want)
        rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
        assert rel < 1e-4, rel


def test_cross_attention_kv_length():
    rng = np.random.RandomState(2)
    B, Sq, Skv, H, D = 2, 64, 160, 2, 32
    q = rng.randn(B, Sq, H, D).astype(np.float32)
    k = rng.randn(B, Skv, H, D).astype(np.float32)
    v = rng.randn(B, Skv, H, D).astype(np.float32)
    out, _ = F.flash_attention(paddle.to_tensor(q), paddle.to_tensor(k),
                               paddle.to_tensor(v))
    out = out.numpy()
    ref = np.asarray(_dense(q, k, v, False, 1 / np.sqrt(D)))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=2e-5)


def test_dropout_rejected_and_scale():
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(1, 32, 2, 16).astype(np.float32))
    with pytest.raises(ValueError, match="dropout"):
        F.flash_attention(x, x, x, dropout=0.1)
    with pytest.raises(ValueError, match="return_softmax"):
        F.flash_attention(x, x, x, return_softmax=True)
    # custom scale honored
    out1, _ = F.flash_attention(x, x, x, scale=0.5)
    out1 = out1.numpy()
    ref = np.asarray(_dense(x.numpy(), x.numpy(), x.numpy(), False, 0.5))
    np.testing.assert_allclose(out1, ref, rtol=1e-4, atol=2e-5)


@pytest.mark.parametrize("bq,bk", [(32, 48), (48, 32), (16, 128)])
def test_block_size_boundaries_causal(bq, bk):
    """The causal early-exit arithmetic (n_k ceil and the dkv start
    block) under block_q != block_k — fwd and grads."""
    rng = np.random.RandomState(4)
    B, S, H, D = 1, 160, 2, 32
    q = rng.randn(B, S, H, D).astype(np.float32) * 0.5
    k = rng.randn(B, S, H, D).astype(np.float32) * 0.5
    v = rng.randn(B, S, H, D).astype(np.float32)
    qt, kt, vt = map(paddle.to_tensor, (q, k, v))
    for t in (qt, kt, vt):
        t.stop_gradient = False
    out, _ = F.flash_attention(qt, kt, vt, causal=True, block_q=bq,
                               block_k=bk)
    ref = np.asarray(_dense(q, k, v, True, 1 / np.sqrt(D)))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=2e-5)
    (out * out).sum().backward()

    def loss(q, k, v):
        o = _dense(q, k, v, True, 1 / np.sqrt(D))
        return jnp.sum(o * o)
    gq, gk, gv = jax.grad(loss, (0, 1, 2))(q, k, v)
    for got, want in [(qt.grad, gq), (kt.grad, gk), (vt.grad, gv)]:
        got, want = np.asarray(got.numpy()), np.asarray(want)
        rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
        assert rel < 1e-4, rel
