"""Expert parallelism: Switch-style MoE over the "ep" mesh axis with
all_to_all token dispatch (parity-plus; the reference snapshot has no MoE).
Forward checked exactly against a per-token dense reference."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet import moe_ffn, MoELayer


@pytest.fixture
def ep_mesh():
    dist.set_mesh(dist.build_mesh({"ep": 8}))
    yield dist.get_mesh()
    dist.set_mesh(None)


def _params(seed=0, D=16, F=32, E=8):
    rng = np.random.RandomState(seed)
    wg = rng.randn(D, E).astype(np.float32) * 0.5
    w1 = rng.randn(E, D, F).astype(np.float32) * 0.1
    w2 = rng.randn(E, F, D).astype(np.float32) * 0.1
    return wg, w1, w2


def _dense_ref(x, wg, w1, w2):
    B, T, D = x.shape
    xt = x.reshape(-1, D)
    logits = xt @ wg
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    e = p.argmax(-1)
    gp = p.max(-1)
    y = np.zeros_like(xt)
    for i in range(xt.shape[0]):
        h = xt[i] @ w1[e[i]]
        h = 0.5 * h * (1 + np.tanh(np.sqrt(2 / np.pi)
                                   * (h + 0.044715 * h ** 3)))
        y[i] = gp[i] * (h @ w2[e[i]])
    return y.reshape(B, T, D)


class TestMoE:
    def test_forward_matches_dense(self, ep_mesh):
        rng = np.random.RandomState(0)
        x = rng.randn(8, 4, 16).astype(np.float32)
        wg, w1, w2 = _params()
        out, aux = moe_ffn(jnp.asarray(x), jnp.asarray(wg),
                           jnp.asarray(w1), jnp.asarray(w2),
                           mesh=ep_mesh, capacity_factor=8.0)
        np.testing.assert_allclose(np.asarray(out),
                                   _dense_ref(x, wg, w1, w2),
                                   rtol=2e-3, atol=2e-4)
        assert float(aux) > 0

    @pytest.mark.slow
    def test_capacity_drops_overflow(self, ep_mesh):
        # gate forced to expert 0: with tiny capacity most tokens drop
        rng = np.random.RandomState(1)
        # positive inputs so the linear gate really sends EVERY token to
        # expert 0 (zero-mean inputs would flip sign per token)
        x = (np.abs(rng.randn(8, 4, 16)) + 0.1).astype(np.float32)
        wg = np.zeros((16, 8), np.float32)
        wg[:, 0] = 10.0 / 16
        _, w1, w2 = _params(1)
        out, _ = moe_ffn(jnp.asarray(x), jnp.asarray(wg * 100),
                         jnp.asarray(w1), jnp.asarray(w2),
                         mesh=ep_mesh, capacity_factor=0.25)
        dropped = np.asarray(out).reshape(-1, 16)
        # capacity = ceil(4 * 0.25 / 8 * ... ) = 1 per expert per rank:
        # exactly 1 token per rank routed, the other 3 zeroed
        zero_rows = (np.abs(dropped).sum(-1) < 1e-7).sum()
        assert zero_rows == 8 * 4 - 8

    @pytest.mark.slow
    def test_training_decreases_loss(self, ep_mesh):
        rng = np.random.RandomState(2)
        x = rng.randn(8, 4, 16).astype(np.float32)
        y = rng.randn(8, 4, 16).astype(np.float32)
        wg, w1, w2 = _params(2)

        def loss_fn(params):
            o, aux = moe_ffn(jnp.asarray(x), *params, mesh=ep_mesh,
                             capacity_factor=8.0)
            return jnp.mean((o - jnp.asarray(y)) ** 2) + 0.01 * aux

        params = tuple(jnp.asarray(a) for a in (wg, w1, w2))
        l1, g = jax.value_and_grad(loss_fn)(params)
        assert all(np.abs(np.asarray(gi)).sum() > 0 for gi in g)
        params = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg,
                                        params, g)
        l2 = loss_fn(params)
        assert float(l2) < float(l1)

    @pytest.mark.slow
    def test_layer_wrapper_tape(self, ep_mesh):
        rng = np.random.RandomState(3)
        x = paddle.to_tensor(rng.randn(8, 4, 16).astype(np.float32),
                             stop_gradient=False)
        wg, w1, w2 = _params(3)
        layer = MoELayer(mesh=ep_mesh, capacity_factor=8.0)
        out, aux = layer(x, paddle.to_tensor(wg, stop_gradient=False),
                         paddle.to_tensor(w1, stop_gradient=False),
                         paddle.to_tensor(w2, stop_gradient=False))
        (out * out).sum().backward()
        assert x.grad is not None and np.abs(x.grad.numpy()).sum() > 0
