"""C++ shared-memory ring transport (csrc/shm_ring.cpp) + DataLoader
integration (reference: memory/allocation/mmap_allocator.cc transport,
reader/buffered_reader.cc)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.shm_ring import ShmRing, available
from paddle_tpu.io import DataLoader, Dataset

pytestmark = pytest.mark.skipif(not available(),
                                reason="no g++/posix shm available")


class TestShmRing:
    def test_bytes_roundtrip_with_wraparound(self):
        ring = ShmRing(f"/pt_t1_{os.getpid()}", capacity=256, create=True)
        try:
            for i in range(10):  # 10 * 100B > 256B: exercises wraparound
                data = bytes([i]) * 100
                ring.push_bytes(data)
                assert ring.pop_bytes(100) == data
        finally:
            ring.close()

    def test_object_roundtrip(self):
        ring = ShmRing(f"/pt_t2_{os.getpid()}", capacity=1 << 20,
                       create=True)
        try:
            obj = {"x": np.arange(100, dtype=np.float32),
                   "y": [np.ones((3, 4))], "meta": "hello"}
            n = ring.push_object(obj)
            out = ring.pop_object(n)
            np.testing.assert_allclose(out["x"], obj["x"])
            np.testing.assert_allclose(out["y"][0], obj["y"][0])
            assert out["meta"] == "hello"
        finally:
            ring.close()

    def test_oversized_payload_raises(self):
        ring = ShmRing(f"/pt_t3_{os.getpid()}", capacity=128, create=True)
        try:
            with pytest.raises(ValueError, match="capacity"):
                ring.push_bytes(b"x" * 1024)
        finally:
            ring.close()


class _ArrDataset(Dataset):
    def __init__(self, n=64):
        self.x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)

    def __getitem__(self, i):
        return self.x[i], np.int64(i)

    def __len__(self):
        return len(self.x)


class TestDataLoaderShm:
    def test_multiworker_shm_matches_single(self):
        ds = _ArrDataset()
        single = [b for b in DataLoader(ds, batch_size=8, num_workers=0,
                                        shuffle=False)]
        multi = [b for b in DataLoader(ds, batch_size=8, num_workers=2,
                                       shuffle=False,
                                       use_shared_memory=True)]
        assert len(single) == len(multi)
        for a, b in zip(single, multi):
            np.testing.assert_allclose(a[0].numpy(), b[0].numpy())
            np.testing.assert_array_equal(a[1].numpy(), b[1].numpy())

    def test_queue_fallback_matches(self):
        ds = _ArrDataset()
        multi = [b for b in DataLoader(ds, batch_size=8, num_workers=2,
                                       shuffle=False,
                                       use_shared_memory=False)]
        assert len(multi) == 8


class TestConcurrentIterators:
    def test_two_live_iterators_do_not_clobber_rings(self):
        """Regression: rings are per-iterator state; a second iterator of
        the same loader must not unlink/overwrite the first one's."""
        ds = _ArrDataset(32)
        dl = DataLoader(ds, batch_size=4, num_workers=2, shuffle=False,
                        use_shared_memory=True)
        it1 = iter(dl)
        first = next(it1)
        # full second pass while it1 is still live
        second_pass = [b for b in dl]
        ref = [b for b in DataLoader(ds, batch_size=4, num_workers=0,
                                     shuffle=False)]
        assert len(second_pass) == 8
        for a, b in zip(second_pass, ref):
            np.testing.assert_allclose(a[0].numpy(), b[0].numpy())
            np.testing.assert_array_equal(a[1].numpy(), b[1].numpy())
        # it1 continues draining correctly afterwards
        rest = list(it1)
        got = [first] + rest
        assert len(got) == 8
        for a, b in zip(got, ref):
            np.testing.assert_allclose(a[0].numpy(), b[0].numpy())
            np.testing.assert_array_equal(a[1].numpy(), b[1].numpy())
