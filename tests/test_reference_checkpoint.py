"""Reference-format checkpoint interop (VERDICT r3 item 9): a
reference-style .pdparams fixture (generated locally — no egress) must
round-trip reference -> paddle_tpu -> equal logits, including the
chunked-big-param and paddle-2.1 tuple container quirks.

Format pinned against python/paddle/framework/io.py:672 +
fluid/io.py:1714 (_unpack_saved_dict / _pack_loaded_dict).
"""
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import framework_io
from paddle_tpu.vision import models


@pytest.mark.slow
def test_resnet18_roundtrip_equal_logits(tmp_path):
    paddle.seed(5)
    src_net = models.resnet18(num_classes=10)
    x = np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32)
    ref_logits = src_net(paddle.to_tensor(x)).numpy()

    # write in the REFERENCE on-disk format
    path = str(tmp_path / "resnet18.pdparams")
    framework_io.save_reference_state_dict(src_net.state_dict(), path)
    # the file must carry the reference's name-table key
    with open(path, "rb") as f:
        blob = pickle.load(f)
    assert "StructuredToParameterName@@" in blob
    assert all(isinstance(v, np.ndarray) for k, v in blob.items()
               if k != "StructuredToParameterName@@")

    # load through the converter into a fresh model
    paddle.seed(99)   # different init, must be fully overwritten
    dst_net = models.resnet18(num_classes=10)
    missing, unexpected = framework_io.convert_reference_checkpoint(
        path, dst_net)
    assert missing == [] and unexpected == []
    np.testing.assert_allclose(dst_net(paddle.to_tensor(x)).numpy(),
                               ref_logits, rtol=1e-5, atol=1e-6)


def test_pretrained_path_loads(tmp_path):
    paddle.seed(6)
    src = models.resnet18(num_classes=4)
    path = str(tmp_path / "w.pdparams")
    framework_io.save_reference_state_dict(src.state_dict(), path)
    net = models.resnet18(pretrained=path, num_classes=4)
    x = np.random.RandomState(1).randn(1, 3, 32, 32).astype(np.float32)
    np.testing.assert_allclose(net(paddle.to_tensor(x)).numpy(),
                               src(paddle.to_tensor(x)).numpy(), rtol=1e-5)


def test_chunked_big_param_reassembly(tmp_path):
    # protocol-2 chunking path (fluid/io.py:1714): force a tiny threshold
    sd = {"w": np.arange(10, dtype=np.float32).reshape(2, 5),
          "b": np.ones(3, np.float32)}
    path = str(tmp_path / "chunked.pdparams")
    framework_io.save_reference_state_dict(sd, path, protocol=2,
                                           _max_elements=4)
    with open(path, "rb") as f:
        blob = pickle.load(f)
    assert "UnpackBigParamInfor@@" in blob
    assert "w@@.0" in blob and "w@@.1" in blob and "w" not in blob
    out = framework_io.load_reference_state_dict(path)
    np.testing.assert_allclose(out["w"], sd["w"])
    np.testing.assert_allclose(out["b"], sd["b"])


def test_tuple_entries_and_validation(tmp_path):
    # paddle-2.1 tuple form (io.py:327) + strict-mode errors
    path = str(tmp_path / "t.pdparams")
    with open(path, "wb") as f:
        pickle.dump({"w": ("linear_0.w_0", np.ones((2, 2), np.float32)),
                     "StructuredToParameterName@@": {}}, f)
    out = framework_io.load_reference_state_dict(path)
    np.testing.assert_allclose(out["w"], 1.0)

    net = paddle.nn.Linear(2, 2)
    with pytest.raises(ValueError, match="missing"):
        framework_io.convert_reference_checkpoint(path, net)
    # shape conflict
    path2 = str(tmp_path / "t2.pdparams")
    with open(path2, "wb") as f:
        pickle.dump({"weight": np.ones((3, 3), np.float32),
                     "bias": np.ones(2, np.float32)}, f)
    with pytest.raises(ValueError, match="shape"):
        framework_io.convert_reference_checkpoint(path2, net)
