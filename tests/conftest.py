"""Test configuration: run everything on XLA-CPU with 8 virtual devices so
multi-chip sharding tests execute without TPU hardware (SURVEY §4 TPU
equivalent: `XLA_FLAGS=--xla_force_host_platform_device_count=8`)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The axon TPU plugin in this image overrides JAX_PLATFORMS from the
# environment; the config route sticks.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield
