"""Test configuration: run everything on XLA-CPU with 8 virtual devices so
multi-chip sharding tests execute without TPU hardware (SURVEY §4 TPU
equivalent: `XLA_FLAGS=--xla_force_host_platform_device_count=8`)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The axon TPU plugin in this image overrides JAX_PLATFORMS from the
# environment; the config route sticks.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu as paddle
    paddle.seed(2024)
    np.random.seed(2024)
    yield


# Per-test wall-clock timeout (reference: the test scheduler's per-UT
# timeout, unittests/CMakeLists.txt set_tests_properties TIMEOUT). No
# pytest-timeout in this image, so a SIGALRM guard: default 300 s, override
# with @pytest.mark.timeout_s(N).
import signal  # noqa: E402


@pytest.fixture(autouse=True)
def _per_test_timeout(request):
    marker = request.node.get_closest_marker("timeout_s")
    limit = int(marker.args[0]) if marker else 300

    def _alarm(signum, frame):
        raise TimeoutError(
            f"test exceeded {limit}s wall-clock (per-test timeout guard)")
    if hasattr(signal, "SIGALRM"):
        old = signal.signal(signal.SIGALRM, _alarm)
        signal.alarm(limit)
        yield
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
    else:  # pragma: no cover
        yield


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "timeout_s(n): per-test wall-clock limit in seconds")
