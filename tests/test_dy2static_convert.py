"""Mode-equivalence for AST-converted plain-Python control flow under
to_static (reference: dygraph_to_static test suite —
test_ifelse.py/test_loop.py discipline: the SAME unmodified dygraph code
must produce identical results eager vs static)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import jit


def T(x, sg=True):
    return paddle.to_tensor(np.asarray(x), stop_gradient=sg)


# -- plain functions with tensor ifs ----------------------------------------

def branchy(x):
    # data-dependent if on a tensor value: the reference converts this via
    # ifelse_transformer; unconverted it is an XLA tracer error
    if x.mean() > 0:
        y = x * 2.0
    else:
        y = x - 1.0
    return y


def nested_branch(x):
    if x.sum() > 0:
        if x.sum() > 10:
            out = x * 3.0
        else:
            out = x * 2.0
    else:
        out = x * 0.5
    return out


def while_counter(x):
    # tensor-ranged while: loop count depends on data
    i = paddle.to_tensor(np.float32(0.0))
    s = x.sum() * 0.0
    while i < 5.0:
        s = s + x.mean()
        i = i + 1.0
    return s


def helper_double(v):
    if v.mean() > 0:
        r = v * 2.0
    else:
        r = v
    return r


def calls_helper(x):
    # convert_call one level deep: helper_double's tensor-if converts too
    y = helper_double(x)
    return y + 1.0


class TestConvertedFunctions:
    @pytest.mark.parametrize("fn,xs", [
        (branchy, [np.ones((2, 3)), -np.ones((2, 3))]),
        (nested_branch, [np.ones((2, 3)), np.full((2, 3), 4.0),
                         -np.ones((2, 3))]),
        (while_counter, [np.ones((2, 3)) * 3.0]),
        (calls_helper, [np.ones((2, 3)), -np.ones((2, 3))]),
    ])
    def test_eager_equals_static(self, fn, xs):
        static_fn = jit.to_static(fn)
        for x in xs:
            x32 = x.astype(np.float32)
            eager = fn(T(x32))
            static = static_fn(T(x32))
            np.testing.assert_allclose(static.numpy(), eager.numpy(),
                                       rtol=1e-6)

    def test_python_bool_if_still_python(self):
        # runtime dispatch: a non-tensor predicate stays a Python branch
        def f(x, flag=True):
            if flag:
                y = x + 1.0
            else:
                y = x - 1.0
            return y
        sf = jit.to_static(f)
        np.testing.assert_allclose(sf(T(np.zeros(3, np.float32))).numpy(),
                                   1.0)

    def test_grad_through_converted_if(self):
        def f(x):
            if x.mean() > 0:
                y = (x * 3.0).sum()
            else:
                y = (x * 5.0).sum()
            return y
        sf = jit.to_static(f)
        x = T(np.ones(4, np.float32), sg=False)
        sf(x).backward()
        np.testing.assert_allclose(x.grad.numpy(), 3.0)
        x2 = T(-np.ones(4, np.float32), sg=False)
        sf(x2).backward()
        np.testing.assert_allclose(x2.grad.numpy(), 5.0)

    def test_return_inside_branch_falls_back_with_clear_error(self):
        def f(x):
            if x.mean() > 0:
                return x * 2.0
            return x - 1.0
        sf = jit.to_static(f)
        with pytest.raises(Exception) as e:
            sf(T(np.ones(3, np.float32)))
        # the pre-existing guidance error, not silent wrong results
        assert "cond" in str(e.value) or "Tracer" in str(
            type(e.value).__name__) or "concret" in str(e.value).lower()

    def test_disable_flag_restores_old_behavior(self):
        jit.enable_ast_conversion(False)
        try:
            sf = jit.to_static(branchy)
            with pytest.raises(Exception):
                sf(T(np.ones((2, 3), np.float32)))
        finally:
            jit.enable_ast_conversion(True)


# -- reference-style models --------------------------------------------------

class MnistWithBranch(nn.Layer):
    """MNIST-ish classifier whose forward takes a data-dependent branch
    (reference: test_ifelse dygraph models)."""

    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(64, 32)
        self.fc2 = nn.Linear(32, 10)
        self.fc_cold = nn.Linear(32, 10)

    def forward(self, x):
        h = paddle.nn.functional.relu(self.fc1(x))
        if h.mean() > 0.1:
            logits = self.fc2(h)
        else:
            logits = self.fc_cold(h)
        return logits


class WhileCounterModel(nn.Layer):
    """Accumulates a recurrence for a data-dependent number of steps
    (reference: test_loop dygraph models)."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(8, 8)

    def forward(self, x, n):
        i = n * 0.0
        h = x
        while i < n:
            h = paddle.tanh(self.fc(h))
            i = i + 1.0
        return h.sum(axis=-1)


class TestConvertedModels:
    def test_mnist_branch_eager_equals_static(self):
        m = MnistWithBranch()
        x_warm = np.random.RandomState(0).randn(4, 64).astype(np.float32) + 1
        x_cold = np.random.RandomState(1).randn(4, 64).astype(np.float32) - 5
        eager_w = m(T(x_warm)).numpy()
        eager_c = m(T(x_cold)).numpy()
        sm = jit.to_static(MnistWithBranch())
        sm.set_state_dict(m.state_dict())
        np.testing.assert_allclose(sm(T(x_warm)).numpy(), eager_w, rtol=1e-5)
        np.testing.assert_allclose(sm(T(x_cold)).numpy(), eager_c, rtol=1e-5)

    def test_while_model_eager_equals_static(self):
        m = WhileCounterModel()
        x = np.random.RandomState(2).randn(2, 8).astype(np.float32)
        for steps in (1.0, 3.0):
            eager = m(T(x), T(np.float32(steps))).numpy()
            sm = jit.to_static(WhileCounterModel())
            sm.set_state_dict(m.state_dict())
            got = sm(T(x), T(np.float32(steps))).numpy()
            np.testing.assert_allclose(got, eager, rtol=1e-5)

    def test_training_through_converted_branch(self):
        # gradients flow through the converted if inside a train loop
        m = jit.to_static(MnistWithBranch())
        opt_sgd = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=m.parameters())
        x = np.random.RandomState(3).randn(8, 64).astype(np.float32) + 1
        y = np.random.RandomState(4).randint(0, 10, size=(8,))
        losses = []
        for _ in range(5):
            logits = m(T(x))
            loss = paddle.nn.functional.cross_entropy(
                logits, T(y.astype(np.int64)))
            loss.backward()
            opt_sgd.step()
            opt_sgd.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


class TestConversionFallbacks:
    """Constructs the converter must refuse (review findings): fall back to
    unconverted code, never silently-wrong results."""

    def test_match_statement_in_branch_not_converted(self):
        def f(x, flag=True, mode="a"):
            if flag:
                match mode:
                    case "a":
                        return x * 2.0
                    case _:
                        return x * 3.0
            return x - 1.0
        sf = jit.to_static(f)
        x = T(np.ones(3, np.float32))
        np.testing.assert_allclose(sf(x).numpy(), f(x).numpy())

    def test_return_after_nested_def_detected(self):
        def h(x):
            if x.mean() > 0:
                y = x
                def helper():
                    pass
                helper()
                return x * 99.0
            return x
        sf = jit.to_static(h)
        # conversion must have been refused (escaping return): function
        # still behaves exactly like eager for a concrete-traced... the
        # tensor-pred + return combination keeps the clear tracer error
        with pytest.raises(Exception):
            sf(T(np.ones(3, np.float32)))

    def test_callee_memo_lives_on_function_object(self):
        sf = jit.to_static(calls_helper)
        out = sf(T(np.ones(3, np.float32)))
        np.testing.assert_allclose(out.numpy(), 3.0)
        # the one-level conversion is memoised on the callee itself
        assert "__pt_call_conv__" in helper_double.__dict__
        assert "__pt_call_conv__" not in globals()

    def test_closure_function_falls_back(self):
        # a function closing over locals cannot be recompiled; conversion
        # is refused and the documented tracer error remains
        bias = 7.0

        def f(x):
            if x.mean() > 0:
                y = x + bias
            else:
                y = x - bias
            return y

        sf = jit.to_static(f)
        with pytest.raises(Exception):
            sf(T(np.ones(3, np.float32)))


class TestRound4ReviewFixes:
    """Regression tests for the round-4 review findings on ast_transform."""

    def test_generator_branch_not_resliced(self):
        from paddle_tpu.jit.ast_transform import convert_function

        def gen(flag):
            if flag:
                yield 1
            yield 2

        g2 = convert_function(gen)
        assert list(g2(True)) == [1, 2]
        assert list(g2(False)) == [2]

    def test_yield_inside_branch_refused(self):
        from paddle_tpu.jit.ast_transform import convert_function
        import inspect

        def uses_yield_in_if(flag):
            out = []
            if flag:
                out = [x for x in range(3)]
            return out

        # comprehension is fine (own scope); a genuine generator refuses
        f2 = convert_function(uses_yield_in_if)
        assert f2(True) == [0, 1, 2]

    def test_import_binding_inside_branch(self):
        from paddle_tpu.jit.ast_transform import convert_function

        def f(x, flag=True):
            if flag:
                import math as _m
                y = x + _m.pi
            else:
                y = x
            return y

        f2 = convert_function(f)
        assert abs(f2(1.0) - (1.0 + 3.141592653589793)) < 1e-12
        assert f2(1.0, flag=False) == 1.0

    def test_walrus_in_assign_value(self):
        from paddle_tpu.jit.ast_transform import convert_function

        def f(x, flag=True):
            if flag:
                y = (z := x + 1) + z
        # z bound via walrus inside the branch value must propagate
            else:
                y = x
                z = 0
            return y + z

        f2 = convert_function(f)
        assert f2(1.0) == 6.0          # z=2, y=z+z=4, y+z=6
        assert f2(1.0, flag=False) == 1.0
