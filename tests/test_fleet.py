"""Self-driving serving fleet (docs/serving.md "Fleet operations").

Five invariant families:

* **Park/unpark** — parking is intentional capacity removal (healthz
  stays ``ok``, no budget spent); unparking boots through the budgeted
  resurrection path (a scale-up is a counted restart).
* **Autoscaler** — the hysteresis/cooldown state machine, driven
  tick-by-tick against a fake router so every decision is deterministic:
  breach and calm runs, the cooldown window, the min/max clamps, and the
  stale-latency guard (a p95 reservoir with no fresh traffic is not a
  breach).
* **Hot swap** — version-tagged bitwise output (old weights OR new
  weights, never mixed), zero recompiles across a roll, eligibility
  gates (health stamp), fault-injected rollback to the prior weights.
* **Kill** — the in-process SIGKILL analog fails queued AND in-flight
  requests with ``EngineKilled`` (retryable) instead of hanging them.
* **Degraded router** — an exhausted restart budget degrades service
  gracefully: the ``degraded`` gauge rises, ``/healthz`` reports
  ``degraded``, and the surviving replicas keep serving.

Plus the replay harness (trace determinism, recorder hook, zero-drop
replay) and the RestartBudget curve-reuse pin: the elastic supervisor
and the Router share ONE backoff implementation with independent state.
"""
import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.distributed.elastic import RestartBudget
from paddle_tpu.incubate.checkpoint import commit_checkpoint, swap_eligible
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.observability.metrics import render_prometheus
from paddle_tpu.serving.fleet import (SLO, Autoscaler, AutoscalerConfig,
                                      SwapError, TraceRecorder,
                                      TraceReplayer, WeightSwapper,
                                      load_trace, save_trace,
                                      synthesize_trace)
from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig
from paddle_tpu.serving.request import EngineKilled
from paddle_tpu.serving.router import (NoHealthyReplicas, Router,
                                       RouterConfig, llm_replica_factory)
from paddle_tpu.utils import resilience

VOCAB = 64
PROMPT = [3, 1, 4, 1, 5, 9, 2, 6]


def _tiny_model(seed=0):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


def _llm_cfg(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq", 64)
    kw.setdefault("max_queue", 64)
    kw.setdefault("warmup", False)
    kw.setdefault("default_max_new_tokens", 4)
    return LLMEngineConfig(**kw)


def _mk_router(n=2, seed=0, **rcfg):
    rcfg.setdefault("health_interval", 0.05)
    reg = StatRegistry()
    return Router(
        llm_replica_factory(lambda r: _tiny_model(seed), _llm_cfg()),
        RouterConfig(num_replicas=n, kind="llm", **rcfg),
        registry=reg)


def _wait_for(pred, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


@pytest.fixture
def fault_spec(monkeypatch):
    """Arm PADDLE_TPU_FAULT_SPEC for this test; disarm afterwards."""
    def arm(spec):
        monkeypatch.setenv("PADDLE_TPU_FAULT_SPEC", spec)
        resilience._reset_fault_injector_for_tests()
    yield arm
    monkeypatch.delenv("PADDLE_TPU_FAULT_SPEC", raising=False)
    resilience._reset_fault_injector_for_tests()


# -- park / unpark ------------------------------------------------------------

class TestParkUnpark:
    def test_park_unpark_roundtrip_costs_one_restart(self):
        router = _mk_router(2)
        try:
            assert router.submit(PROMPT).result(timeout=120)["tokens"]
            assert router.park(1) is True
            assert router.park(1) is False          # already parked
            snap = router.fleet_snapshot()
            assert snap["parked"] == [1]
            assert _wait_for(
                lambda: router.fleet_snapshot()["active_replicas"] == 1)
            # parking is NOT degradation: healthz stays ok, service runs
            hz = router.healthz()
            assert hz["status"] == "ok"
            assert hz["parked"] == [1]
            assert hz["degraded_replicas"] == 0
            assert router.submit(PROMPT).result(timeout=120)["tokens"]
            assert router.budget.used == 0          # park is free
            # unpark boots through the budgeted path: one counted restart
            assert router.unpark(1) is True
            assert router.unpark(1) is False        # not parked anymore
            assert router.budget.used == 1
            assert router.replicas[1].state == "HEALTHY"
            assert router.fleet_snapshot()["active_replicas"] == 2
        finally:
            router.drain(timeout=60)

    def test_parked_replica_not_resurrected_by_sweep(self):
        router = _mk_router(2)
        try:
            router.park(1)
            # the sweep must treat a parked DEAD shell as intentional:
            # no budget burn, no resurrection, no degraded accounting
            assert _wait_for(lambda: router.replicas[1].state == "DEAD")
            time.sleep(0.3)                          # several sweep ticks
            assert router.replicas[1].state == "DEAD"
            assert router.budget.used == 0
            stats = router.registry.stats()
            assert stats.get("serving.router.degraded", 0) == 0
        finally:
            router.drain(timeout=60)


# -- autoscaler state machine (fake router: deterministic ticks) --------------

class _FakeRouter:
    """Just enough Router surface for the controller: a snapshot the test
    mutates, park/unpark recording, and a registry."""

    def __init__(self, n=3, parked=()):
        self.replicas = list(range(n))
        self.registry = StatRegistry()
        self._parked = set(parked)
        self.p95_ms = 0.0
        self.queue_depth = 0
        self.completed = 0
        self.rejected = 0.0
        self.lost = 0          # shells dead with no budget (not parked)
        self.park_calls, self.unpark_calls = [], []

    def parked_ids(self):
        return sorted(self._parked)

    def park(self, rid):
        self._parked.add(rid)
        self.park_calls.append(rid)
        return True

    def unpark(self, rid):
        self._parked.discard(rid)
        self.unpark_calls.append(rid)
        return True

    def fleet_snapshot(self):
        reps = [{"replica": i, "parked": i in self._parked,
                 "admissible": i not in self._parked,
                 "outstanding": i, "queue_depth": 0}
                for i in self.replicas]
        return {
            "replicas": reps,
            "active_replicas": (len(self.replicas) - len(self._parked)
                                - self.lost),
            "parked": self.parked_ids(),
            "queue_depth": self.queue_depth,
            "outstanding": 0,
            "p95_ms": self.p95_ms,
            "completed": self.completed,
            "rejected_no_replica": self.rejected,
            "degraded": 0,
            "budget_remaining": 3,
            "draining": False,
        }


class TestAutoscaler:
    def _scaler(self, fake, clock, **cfg):
        cfg.setdefault("breach_ticks", 2)
        cfg.setdefault("calm_ticks", 3)
        cfg.setdefault("cooldown_s", 10.0)
        return Autoscaler(fake, SLO(p95_ms=100.0, max_queue=8,
                                    min_replicas=1),
                          AutoscalerConfig(**cfg),
                          registry=fake.registry, clock=lambda: clock[0])

    def test_breach_hysteresis_then_scale_up(self):
        fake = _FakeRouter(3, parked=(1, 2))
        clock = [0.0]
        sc = self._scaler(fake, clock)
        fake.p95_ms, fake.completed = 500.0, 10
        assert sc.tick()["action"] == "hold"        # breach run 1 of 2
        fake.completed = 20
        assert sc.tick()["action"] == "up"
        assert fake.unpark_calls == [1]             # lowest parked id first
        # cooldown: still breaching, but no second action inside window
        fake.completed = 30
        assert sc.tick()["action"] == "hold"
        fake.completed = 40
        assert sc.tick()["action"] == "hold"
        clock[0] = 11.0                             # past cooldown
        fake.completed = 50
        assert sc.tick()["action"] == "up"
        assert fake.unpark_calls == [1, 2]

    def test_stale_p95_without_traffic_is_not_a_breach(self):
        fake = _FakeRouter(3, parked=(1, 2))
        clock = [0.0]
        sc = self._scaler(fake, clock)
        fake.p95_ms, fake.completed = 500.0, 10
        sc.tick()
        # the latency reservoir still reads 500ms but nothing completed
        # since the last tick: the breach run must RESET, not advance
        assert sc.tick()["breach"] is False
        assert sc.tick()["breach"] is False
        assert fake.unpark_calls == []

    def test_queue_and_reject_axes_breach(self):
        fake = _FakeRouter(3, parked=(1, 2))
        sc = self._scaler(fake, [0.0], breach_ticks=1, cooldown_s=0.0)
        fake.queue_depth = 9
        d = sc.tick()
        assert d["action"] == "up" and "queue" in d["reasons"][0]
        fake.queue_depth = 0
        fake.rejected = 2.0
        d = sc.tick()
        assert d["action"] == "up" and "unplaceable" in d["reasons"][0]

    def test_calm_run_scales_down_to_min(self):
        fake = _FakeRouter(3)
        clock = [0.0]
        sc = self._scaler(fake, clock, calm_ticks=2, cooldown_s=0.0)
        sc.tick()
        d = sc.tick()
        assert d["action"] == "down"
        assert fake.park_calls == [0]     # least outstanding wins
        sc.tick()
        assert sc.tick()["action"] == "down"
        # at min_replicas=1 the fleet never parks its last replica
        for _ in range(5):
            assert sc.tick()["action"] == "hold"
        assert len(fake.park_calls) == 2

    def test_up_blocked_when_capacity_lost_not_parked(self):
        fake = _FakeRouter(2, parked=())
        sc = self._scaler(fake, [0.0], breach_ticks=1, cooldown_s=0.0)
        fake.lost = 1                      # one shell gone for good
        fake.p95_ms, fake.completed = 500.0, 5
        d = sc.tick()
        assert d["action"] == "up_blocked"
        assert fake.registry.stats()["fleet.autoscale.up_blocked"] == 1

    def test_slo_validation(self):
        with pytest.raises(ValueError):
            SLO(min_replicas=0)
        with pytest.raises(ValueError):
            SLO(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            Autoscaler(_FakeRouter(2), SLO(max_replicas=5))


# -- restart-budget curve reuse (elastic supervisor <-> router) ---------------

class TestRestartBudgetCurveReuse:
    def test_router_shares_the_supervisor_budget_class(self):
        router = _mk_router(1)
        try:
            assert isinstance(router.budget, RestartBudget)
        finally:
            router.drain(timeout=60)

    def test_same_curve_independent_state(self):
        """The supervisor's budget and the router's budget are the SAME
        exponential curve (pin the formula) but separate accounting —
        consuming one never moves the other."""
        import random
        sup = RestartBudget(6, backoff=1.0, cap=30.0,
                            rng=random.Random(7))
        rtr = RestartBudget(6, backoff=1.0, cap=30.0,
                            rng=random.Random(7))
        sup_curve, rtr_curve = [], []
        for _ in range(6):
            assert sup.try_consume() and rtr.try_consume()
            sup_curve.append(sup.pause())
            rtr_curve.append(rtr.pause())
        assert sup_curve == rtr_curve              # identical curve
        assert sup.used == rtr.used == 6

        class _Mid:                                 # jitter factor == 1.0
            def random(self):
                return 0.5

        pinned = RestartBudget(8, backoff=0.5, cap=4.0, rng=_Mid())
        seen = []
        for _ in range(5):
            pinned.try_consume()
            seen.append(round(pinned.pause(), 6))
        # backoff * 2**(used-1), capped: 0.5, 1, 2, 4, 4
        assert seen == [0.5, 1.0, 2.0, 4.0, 4.0]

        solo = RestartBudget(3)
        other = RestartBudget(3)
        assert solo.try_consume()
        assert solo.used == 1 and other.used == 0  # independent state


# -- hard kill ----------------------------------------------------------------

class TestKill:
    def test_kill_fails_queued_and_inflight_with_engine_killed(self):
        engine = LLMEngine(_tiny_model(), _llm_cfg(num_slots=1))
        try:
            futs = [engine.submit(PROMPT, max_new_tokens=8)
                    for _ in range(3)]
            engine.kill("test chaos")
            assert engine.was_killed
            for f in futs:
                with pytest.raises(EngineKilled):
                    f.result(timeout=30)
            with pytest.raises(EngineKilled):       # admission slams shut
                engine.submit(PROMPT)
        finally:
            engine.drain(timeout=30)

    def test_router_resurrects_killed_replica(self):
        router = _mk_router(2)
        try:
            assert router.submit(PROMPT).result(timeout=120)["tokens"]
            assert router.replicas[0].kill("test chaos") is True
            assert _wait_for(
                lambda: router.replicas[0].state == "HEALTHY", timeout=30)
            assert router.budget.used >= 1          # counted resurrection
            assert router.submit(PROMPT).result(timeout=120)["tokens"]
        finally:
            router.drain(timeout=60)


# -- live weight hot-swap -----------------------------------------------------

class TestHotSwap:
    def test_swap_requires_paused_admission(self):
        engine = LLMEngine(_tiny_model(), _llm_cfg())
        try:
            with pytest.raises(RuntimeError, match="pause_admission"):
                engine.swap_weights({})
        finally:
            engine.drain(timeout=30)

    def test_classifier_router_refused(self):
        class _Classifier:
            kind = "classifier"
        with pytest.raises(ValueError, match="LLMEngine"):
            WeightSwapper(_Classifier())

    def test_eligibility_gates(self, tmp_path):
        ok, why = swap_eligible(str(tmp_path / "nope"))
        assert not ok
        sick = str(tmp_path / "sick")
        commit_checkpoint({"model": _tiny_model(1).state_dict()}, sick,
                          healthy=False, reason="probe failed")
        ok, why = swap_eligible(sick)
        assert not ok and "health" in why.lower()
        router = _mk_router(1)
        try:
            with pytest.raises(SwapError, match="refusing"):
                WeightSwapper(router).roll(sick)
            assert router.registry.stats()["fleet.swap.refused"] == 1
        finally:
            router.drain(timeout=60)

    def test_roll_is_version_tagged_bitwise_and_recompile_free(
            self, tmp_path):
        # reference output of the NEW weights, from a standalone engine
        ref = LLMEngine(_tiny_model(seed=1), _llm_cfg())
        try:
            want = ref.submit(PROMPT, max_new_tokens=6) \
                      .result(timeout=120)["tokens"]
        finally:
            ref.drain(timeout=30)

        router = _mk_router(1, seed=0)
        try:
            before = router.submit(PROMPT, max_new_tokens=6) \
                           .result(timeout=120)
            assert before["weights_version"] == 0
            assert before["tokens"] != want         # old weights differ

            ckpt = str(tmp_path / "ckpt-new")
            commit_checkpoint({"model": _tiny_model(seed=1).state_dict()},
                              ckpt, healthy=True, step=1)
            engine = router.replicas[0].engine
            misses0 = engine.cache.stats()["misses"]
            report = WeightSwapper(router).roll(ckpt)
            assert report["swapped"] == [0]
            assert report["aborted"] is False
            assert report["versions"] == {0: 1}
            # the whole point of spec-keyed executables: a weight swap
            # costs ZERO recompiles
            assert engine.cache.stats()["misses"] == misses0

            after = router.submit(PROMPT, max_new_tokens=6) \
                          .result(timeout=120)
            assert after["weights_version"] == 1    # tagged at admission
            assert after["tokens"] == want          # bitwise the new model
            assert router.registry.stats()["fleet.swap.replicas_swapped"] \
                == 1
            assert router.registry.quantile("fleet.swap.downtime_ms",
                                            0.95) > 0.0
        finally:
            router.drain(timeout=60)

    def test_failed_swap_rolls_back_to_prior_weights(self, tmp_path,
                                                     fault_spec):
        router = _mk_router(1, seed=0)
        try:
            before = router.submit(PROMPT, max_new_tokens=6) \
                           .result(timeout=120)["tokens"]
            ckpt = str(tmp_path / "ckpt-new")
            commit_checkpoint({"model": _tiny_model(seed=1).state_dict()},
                              ckpt, healthy=True, step=1)
            fault_spec("weight_swap:1:fail")
            report = WeightSwapper(router).roll(ckpt)
            assert report["aborted"] is True
            assert report["rolled_back"] == 0
            assert report["swapped"] == []
            assert router.registry.stats()["fleet.swap.rollbacks"] == 1
            # the replica serves the OLD weights again — bitwise
            after = router.submit(PROMPT, max_new_tokens=6) \
                          .result(timeout=120)
            assert after["tokens"] == before
            assert router.replicas[0].state == "HEALTHY"
        finally:
            router.drain(timeout=60)


# -- degraded router (exhausted budget) ---------------------------------------

class TestDegradedRouter:
    def test_budget_exhaustion_degrades_gracefully(self):
        router = _mk_router(2, max_restarts=0)
        try:
            assert router.submit(PROMPT).result(timeout=120)["tokens"]
            router.replicas[0].kill("chaos: unrecoverable")
            # no budget: the sweep gives up on replica 0 and says so
            assert _wait_for(lambda: router.registry.stats().get(
                "serving.router.degraded", 0) == 1, timeout=30)
            hz = router.healthz()
            assert hz["status"] == "degraded"
            assert hz["degraded_replicas"] == 1
            assert hz["budget_remaining"] == 0
            # ...but the surviving replica still serves traffic
            assert router.submit(PROMPT).result(timeout=120)["tokens"]
            assert router.replicas[0].state == "DEAD"
        finally:
            router.drain(timeout=60)

    def test_degraded_gauge_in_prometheus_exposition(self):
        router = _mk_router(2, max_restarts=0)
        try:
            router.submit(PROMPT).result(timeout=120)
            router.replicas[0].kill("chaos")
            assert _wait_for(lambda: router.registry.stats().get(
                "serving.router.degraded", 0) == 1, timeout=30)
            text = render_prometheus(router.registry)
            assert "paddle_tpu_serving_router_degraded 1" in text
            # per-replica series carry the replica label (satellite of
            # the aggregate /metricsz endpoint)
            assert 'replica="0"' in text and 'replica="1"' in text
            assert "paddle_tpu_serving_router_replica_p95_ms" in text
            assert "paddle_tpu_serving_router_replica_parked" in text
        finally:
            router.drain(timeout=60)


# -- traffic replay -----------------------------------------------------------

class TestReplay:
    def test_synthesize_is_deterministic_and_ordered(self):
        a = synthesize_trace(50, 20.0, seed=3)
        b = synthesize_trace(50, 20.0, seed=3)
        assert a == b
        assert a != synthesize_trace(50, 20.0, seed=4)
        ts = [r["t"] for r in a]
        assert ts == sorted(ts) and ts[0] > 0.0

    def test_trace_roundtrip(self, tmp_path):
        trace = synthesize_trace(10, 50.0, seed=1)
        p = str(tmp_path / "storm.jsonl")
        save_trace(trace, p)
        assert load_trace(p) == trace
        with open(p) as f:                          # one JSON per line
            assert all(json.loads(ln) for ln in f if ln.strip())

    def test_recorder_captures_accepted_requests_only(self):
        router = _mk_router(1)
        try:
            rec = TraceRecorder()
            router.set_trace_recorder(rec)
            router.submit(PROMPT, max_new_tokens=2).result(timeout=120)
            router.submit(PROMPT[:3], max_new_tokens=2).result(timeout=120)
            assert len(rec) == 2
            router.park(0)
            with pytest.raises(NoHealthyReplicas):
                router.submit(PROMPT)
            assert len(rec) == 2                    # rejects not recorded
            trace = rec.trace()
            assert trace[0]["t"] == 0.0
            assert trace[0]["prompt_len"] == len(PROMPT)
            assert trace[1]["prompt_len"] == 3
        finally:
            router.drain(timeout=60)

    def test_replay_completes_with_zero_drops(self):
        router = _mk_router(1)
        try:
            trace = synthesize_trace(6, 30.0, seed=2, max_new_tokens=2,
                                     prompt_len_range=(2, 6))
            rep = TraceReplayer(router, trace, vocab=VOCAB,
                                workers=4).run()
            assert rep["offered"] == 6
            assert rep["completed"] == 6
            assert rep["dropped"] == 0
            assert rep["weights_versions"] == {0: 6}
            assert rep["latency_p95_ms"] > 0.0
        finally:
            router.drain(timeout=60)


# -- the chaos storm end-to-end (the --bench-fleet gate, scaled down) ---------

@pytest.mark.slow
class TestChaosStorm:
    def test_storm_with_kill_swap_and_enospc_recovers(self, tmp_path):
        from tools import bench_fleet
        spec_before = {k: os.environ.get(k) for k in
                       ("PADDLE_TPU_FAULT_SPEC",
                        "PADDLE_TPU_FAULT_SLOW_IO_S")}
        try:
            rc = bench_fleet.main([
                "--requests", "60", "--rate", "10", "--tick-s", "0.2",
                "--check", "--baseline",
                str(tmp_path / "missing.json")])    # structural gates only
            assert rc == 0
        finally:
            # the bench arms the process-wide injector; disarm it so
            # later tests in this process see a clean environment
            for k, v in spec_before.items():
                os.environ.pop(k, None)
                if v is not None:
                    os.environ[k] = v
            resilience._reset_fault_injector_for_tests()
