"""Elastic launcher tests (docs/fault_tolerance.md).

Supervisor semantics (restart budget, free preemption restarts, workerlog
tailing, graceful drain) are exercised in-process with throwaway stdlib
child scripts — no paddle import per child, so they're tier-1 fast. The
end-to-end proof (injected crash at epoch 3 of 4 under ``--elastic``,
bit-identical final state vs an uninterrupted run) runs the real CLI.
"""
import os
import signal
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.launch import ElasticSupervisor, _tail_log
from paddle_tpu.distributed.elastic import (PREEMPTION_EXIT_CODE,
                                            ELASTIC_ENV_VAR)
from paddle_tpu.utils.resilience import FAULT_CRASH_EXIT_CODE


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def _supervise(tmp_path, script, max_restarts=2, grace_period=5.0,
               log_dir=None, capsys=None):
    sup = ElasticSupervisor(
        ["127.0.0.1:0"], script, [], log_dir=log_dir,
        max_restarts=max_restarts, grace_period=grace_period,
        restart_backoff=0.05, poll_interval=0.05)
    return sup, sup.run()


class TestElasticSupervisor:
    def test_crash_once_then_succeed(self, tmp_path, capsys):
        marker = tmp_path / "ran_once"
        script = _write(tmp_path, "child.py", f"""
            import os, sys
            m = {str(marker)!r}
            if not os.path.exists(m):
                open(m, "w").write("x")
                sys.exit(7)   # first incarnation crashes
            sys.exit(0)       # restarted incarnation succeeds
        """)
        sup, rc = _supervise(tmp_path, script, max_restarts=2)
        assert rc == 0
        assert sup.restarts_used == 1
        err = capsys.readouterr().err
        assert "exited with code 7" in err and "restarting in" in err

    def test_restart_budget_exhaustion_propagates_exit_code(
            self, tmp_path, capsys):
        script = _write(tmp_path, "child.py", """
            import sys
            print("boom-diagnostic-line", flush=True)
            sys.exit(9)
        """)
        log_dir = str(tmp_path / "logs")
        sup, rc = _supervise(tmp_path, script, max_restarts=1,
                             log_dir=log_dir)
        assert rc == 9
        assert sup.restarts_used == 1
        err = capsys.readouterr().err
        assert "budget (1) exhausted" in err
        # the dead rank's workerlog was tailed into supervisor stderr
        assert "workerlog.0 (tail)" in err
        assert "boom-diagnostic-line" in err

    def test_preemption_exit_restarts_for_free(self, tmp_path, capsys):
        marker = tmp_path / "preempted_once"
        ok = tmp_path / "finished"
        script = _write(tmp_path, "child.py", f"""
            import os, sys
            assert os.environ.get({ELASTIC_ENV_VAR!r}) == "1"
            m = {str(marker)!r}
            if not os.path.exists(m):
                open(m, "w").write("x")
                sys.exit({PREEMPTION_EXIT_CODE})  # drained after preemption
            open({str(ok)!r}, "w").write("x")
            sys.exit(0)
        """)
        # max_restarts=0: only a free (preemption) restart can succeed
        sup, rc = _supervise(tmp_path, script, max_restarts=0)
        assert rc == 0
        assert ok.exists()
        assert sup.restarts_used == 0
        assert "free" in capsys.readouterr().err

    def test_restart_env_counter_and_workerlog_append(self, tmp_path):
        script = _write(tmp_path, "child.py", """
            import os, sys
            n = int(os.environ["PADDLE_TPU_RESTART_NUM"])
            print("incarnation", n, flush=True)
            sys.exit(5 if n == 0 else 0)
        """)
        log_dir = str(tmp_path / "logs")
        sup, rc = _supervise(tmp_path, script, max_restarts=1,
                             log_dir=log_dir)
        assert rc == 0
        log = open(os.path.join(log_dir, "workerlog.0")).read()
        # both incarnations in ONE file, separated by a restart marker
        assert "incarnation 0" in log and "incarnation 1" in log
        assert "----- restart 1 -----" in log

    def test_graceful_drain_on_sigterm(self, tmp_path, capsys):
        drained = tmp_path / "drained"
        started = tmp_path / "started"
        script = _write(tmp_path, "child.py", f"""
            import os, signal, sys, time
            def onterm(signum, frame):
                open({str(drained)!r}, "w").write("x")
                sys.exit({PREEMPTION_EXIT_CODE})
            signal.signal(signal.SIGTERM, onterm)
            open({str(started)!r}, "w").write("x")
            time.sleep(60)
        """)
        sup = ElasticSupervisor(
            ["127.0.0.1:0"], script, [], max_restarts=2,
            grace_period=10.0, restart_backoff=0.05, poll_interval=0.05)

        def drain_when_started():
            import time
            for _ in range(400):
                if started.exists():
                    break
                time.sleep(0.05)
            sup.request_drain()

        t = threading.Thread(target=drain_when_started)
        t.start()
        rc = sup.run()
        t.join()
        assert rc == 1
        assert drained.exists()  # child got SIGTERM and drained in grace
        assert "draining" in capsys.readouterr().err

    def test_tail_log_missing_file(self):
        assert _tail_log(None) == ""
        assert _tail_log("/nonexistent/x.log") == ""


TRAIN_SCRIPT = """
    import os, sys
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, "/root/repo")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.incubate.checkpoint import TrainEpochRange

    ckpt_dir, out_npz = sys.argv[1], sys.argv[2]
    paddle.seed(7)
    net = nn.Linear(4, 2)
    opt = optim.SGD(learning_rate=0.05, parameters=net.parameters())
    rng = np.random.RandomState(0)
    X = rng.randn(16, 4).astype(np.float32)
    Y = rng.randn(16, 2).astype(np.float32)

    r = TrainEpochRange(4, "job_e2e", model=net, optimizer=opt,
                        checkpoint_path=ckpt_dir)
    for epoch in r:
        x, y = paddle.to_tensor(X), paddle.to_tensor(Y)
        loss = paddle.mean((net(x) - y) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        print("epoch", epoch, "loss", float(loss.numpy()), flush=True)

    state = {k: np.asarray(v.numpy())
             for k, v in net.state_dict().items()}
    np.savez(out_npz, **state)
    print("TRAIN DONE", flush=True)
"""


class TestElasticEndToEnd:
    def test_injected_crash_resumes_bit_identical(self, tmp_path):
        """Acceptance proof: --elastic --max_restarts 2 + crash injected at
        epoch 3 of 4 → job completes rc 0 and the restored run's final
        state_dict is bit-identical (CPU) to an uninterrupted run."""
        script = _write(tmp_path, "train.py", TRAIN_SCRIPT)
        env_base = {k: v for k, v in os.environ.items()}

        # uninterrupted reference run (no launcher, no faults)
        out_a = str(tmp_path / "a.npz")
        proc = subprocess.run(
            [sys.executable, script, str(tmp_path / "ckA"), out_a],
            capture_output=True, text=True, timeout=240, env=env_base,
            cwd="/root/repo")
        assert proc.returncode == 0, (proc.stdout, proc.stderr)

        # elastic run: hard crash at the start of the 3rd epoch iteration
        out_b = str(tmp_path / "b.npz")
        env = dict(env_base)
        env["PADDLE_TPU_FAULT_SPEC"] = "epoch:3:crash"
        log_dir = str(tmp_path / "logs")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--elastic", "--max_restarts", "2", "--restart_backoff", "0.1",
             "--log_dir", log_dir, script, str(tmp_path / "ckB"), out_b],
            capture_output=True, text=True, timeout=420, env=env,
            cwd="/root/repo")
        assert proc.returncode == 0, (proc.stdout, proc.stderr)
        assert f"exited with code {FAULT_CRASH_EXIT_CODE}" in proc.stderr
        assert "restarting in" in proc.stderr
        log = open(os.path.join(log_dir, "workerlog.0")).read()
        assert "[FaultInjector] crash at epoch:3" in log
        assert "TRAIN DONE" in log

        a, b = np.load(out_a), np.load(out_b)
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            assert a[k].dtype == b[k].dtype
            assert np.array_equal(a[k], b[k]), (
                f"state {k} diverged after crash+resume")
