"""Standalone predictor over the StableHLO artifact (reference:
inference/api/analysis_predictor.h:82; deploy-without-framework-code)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.static import InputSpec


class TestPredictor:
    def _export(self, tmp_path):
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 3)

            def forward(self, x):
                return nn.functional.softmax(self.fc(x), axis=-1)

        net = Net()
        prefix = str(tmp_path / "model")
        paddle.jit.save(net, prefix,
                        input_spec=[InputSpec([2, 4], "float32", "x")])
        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        return prefix, x, net(paddle.to_tensor(x)).numpy()

    def test_positional_run(self, tmp_path):
        prefix, x, ref = self._export(tmp_path)
        pred = create_predictor(Config(prefix + ".pdmodel"))
        out = pred.run([x])
        np.testing.assert_allclose(out[0], ref, rtol=1e-5)

    def test_handle_api(self, tmp_path):
        prefix, x, ref = self._export(tmp_path)
        pred = create_predictor(Config(prefix))
        names = pred.get_input_names()
        assert len(names) == 1
        h = pred.get_input_handle(names[0])
        h.copy_from_cpu(x)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0])
        np.testing.assert_allclose(out.copy_to_cpu(), ref, rtol=1e-5)
