"""to_static + static Program/Executor tests
(pattern: reference unittests/dygraph_to_static/ mode-equivalence suite +
book/ static-graph chapter tests)."""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
import paddle_tpu.optimizer as optim
from paddle_tpu.jit import to_static, InputSpec


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(8, 32)
        self.bn = nn.BatchNorm1D(32)
        self.drop = nn.Dropout(0.3)
        self.l2 = nn.Linear(32, 2)

    def forward(self, x):
        return self.l2(self.drop(F.relu(self.bn(self.l1(x)))))


class TestToStatic:
    def test_eager_equivalence(self):
        m = SmallNet()
        m.eval()
        x = paddle.randn([16, 8])
        eager = m.forward(x).numpy()  # direct call, no compile
        sm = to_static(m)
        np.testing.assert_allclose(eager, sm(x).numpy(), atol=1e-5)

    def test_training_through_compiled(self):
        paddle.seed(0)
        m = to_static(SmallNet())
        m.train()
        opt = optim.Adam(1e-2, parameters=m.parameters())
        x = paddle.randn([16, 8])
        y = paddle.to_tensor(np.random.randint(0, 2, 16))
        prev_mean = m.bn._mean.numpy().copy()
        losses = []
        for _ in range(25):
            loss = F.cross_entropy(m(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7
        # BN running stats updated through state-effect capture
        assert not np.allclose(prev_mean, m.bn._mean.numpy())

    def test_rng_varies_across_calls(self):
        m = to_static(SmallNet())
        m.train()
        x = paddle.randn([8, 8])
        a, b = m(x).numpy(), m(x).numpy()
        assert not np.allclose(a, b)

    def test_cache_per_shape(self):
        m = to_static(SmallNet())
        m.eval()
        m(paddle.randn([4, 8]))
        m(paddle.randn([6, 8]))
        assert len(m.forward._cache) == 2
        m(paddle.randn([4, 8]))
        assert len(m.forward._cache) == 2

    def test_function_decorator(self):
        @to_static
        def f(a, b):
            return paddle.matmul(a, b) + 1.0
        x = paddle.randn([3, 4])
        y = paddle.randn([4, 5])
        np.testing.assert_allclose(
            f(x, y).numpy(), (paddle.matmul(x, y) + 1.0).numpy(), atol=1e-5)

    def test_grad_matches_eager(self):
        m1 = SmallNet()
        m2 = SmallNet()
        m2.set_state_dict(m1.state_dict())
        m1.eval(); m2.eval()
        sm2 = to_static(m2)
        x = paddle.randn([4, 8])
        y = paddle.to_tensor([0, 1, 0, 1])
        l1 = F.cross_entropy(m1.forward(x), y)
        l1.backward()
        l2 = F.cross_entropy(sm2(x), y)
        l2.backward()
        for (n1, p1), (n2, p2) in zip(m1.named_parameters(),
                                      m2.named_parameters()):
            assert p2.grad is not None, n2
            np.testing.assert_allclose(p1.grad.numpy(), p2.grad.numpy(),
                                       atol=1e-4, err_msg=n1)

    def test_jit_save_load(self, tmp_path):
        m = to_static(SmallNet())
        m.eval()
        x = paddle.randn([4, 8])
        expected = m(x).numpy()
        path = str(tmp_path / "net")
        paddle.jit.save(m, path, input_spec=[InputSpec([4, 8], "float32")])
        loaded = paddle.jit.load(path)
        np.testing.assert_allclose(loaded(x).numpy(), expected, atol=1e-5)


class TestStaticMode:
    def _build(self):
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data("x", [None, 4], "float32")
            y = paddle.static.data("y", [None, 1], "float32")
            lin = nn.Linear(4, 1)
            pred = lin(x)
            loss = paddle.mean((pred - y) ** 2)
        return main, startup, x, y, pred, loss, lin

    def test_static_train_converges(self):
        paddle.enable_static()
        try:
            main, startup, x, y, pred, loss, lin = self._build()
            with paddle.static.program_guard(main, startup):
                opt = optim.SGD(0.1)
                opt.minimize(loss)
            exe = paddle.static.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            X = rng.rand(64, 4).astype(np.float32)
            W = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
            Y = X @ W
            for _ in range(300):
                out, = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[loss])
            assert out < 1e-3
            np.testing.assert_allclose(lin.weight.numpy(), W, atol=0.2)
        finally:
            paddle.disable_static()

    def test_static_infer_only(self):
        paddle.enable_static()
        try:
            main, startup, x, y, pred, loss, lin = self._build()
            exe = paddle.static.Executor()
            X = np.random.rand(5, 4).astype(np.float32)
            Y = np.zeros((5, 1), np.float32)
            p, = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[pred])
            expected = X @ lin.weight.numpy() + lin.bias.numpy()
            np.testing.assert_allclose(p, expected, atol=1e-5)
        finally:
            paddle.disable_static()

    def test_append_backward_fetch_grads(self):
        paddle.enable_static()
        try:
            main, startup, x, y, pred, loss, lin = self._build()
            with paddle.static.program_guard(main, startup):
                pgs = paddle.static.append_backward(loss)
            exe = paddle.static.Executor()
            X = np.ones((2, 4), np.float32)
            Y = np.zeros((2, 1), np.float32)
            grad_vars = [g for _, g in pgs]
            outs = exe.run(main, feed={"x": X, "y": Y},
                           fetch_list=[loss] + grad_vars)
            assert len(outs) == 3  # loss + w grad + b grad
            assert outs[1].shape == (4, 1)
        finally:
            paddle.disable_static()

    def test_dynamic_batch_dim(self):
        paddle.enable_static()
        try:
            main, startup, x, y, pred, loss, lin = self._build()
            exe = paddle.static.Executor()
            for bs in (3, 7):
                X = np.random.rand(bs, 4).astype(np.float32)
                Y = np.zeros((bs, 1), np.float32)
                p, = exe.run(main, feed={"x": X, "y": Y}, fetch_list=[pred])
                assert p.shape == (bs, 1)
        finally:
            paddle.disable_static()

    def test_program_repr_and_clone(self):
        paddle.enable_static()
        try:
            main, startup, *_ , loss, lin = self._build()
            s = str(main)
            assert "linear" in s and "reduce_mean" in s
            test_prog = main.clone(for_test=True)
            assert len(test_prog.ops) == len(main.ops)
        finally:
            paddle.disable_static()


class TestStaticDataParallel:
    """Round-3 (VERDICT weak #4): CompiledProgram.with_data_parallel must
    actually shard feeds over the mesh — numerics must match the
    single-device run (reference: ParallelExecutor semantics)."""

    def test_dp_matches_single_device(self):
        import paddle_tpu.distributed as dist

        def build_and_train(dp):
            paddle.enable_static()
            try:
                paddle.seed(3)
                main = paddle.static.Program()
                startup = paddle.static.Program()
                with paddle.static.program_guard(main, startup):
                    x = paddle.static.data("x", [None, 4], "float32")
                    y = paddle.static.data("y", [None, 2], "float32")
                    lin = nn.Linear(4, 2)
                    loss = paddle.mean((lin(x) - y) ** 2)
                    opt = optim.SGD(learning_rate=0.1)
                    opt._parameter_list = lin.parameters()
                    opt.minimize(loss)
                exe = paddle.static.Executor()
                exe.run(startup)
                prog = main
                if dp:
                    prog = paddle.static.CompiledProgram(
                        main).with_data_parallel(loss_name="loss")
                rng = np.random.RandomState(0)
                X = rng.randn(16, 4).astype(np.float32)
                Y = rng.randn(16, 2).astype(np.float32)
                losses = [exe.run(prog, feed={"x": X, "y": Y},
                                  fetch_list=[loss])[0] for _ in range(3)]
                return np.asarray(losses).ravel(), lin.weight.numpy()
            finally:
                paddle.disable_static()

        dist.set_mesh(dist.build_mesh({"dp": 8}))
        try:
            l_dp, w_dp = build_and_train(dp=True)
        finally:
            dist.set_mesh(None)
        l_single, w_single = build_and_train(dp=False)
        np.testing.assert_allclose(l_dp, l_single, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(w_dp, w_single, rtol=1e-5, atol=1e-6)


class TestEMAAndTracedLayer:
    """Round-3: ExponentialMovingAverage (reference: fluid/optimizer.py:3694)
    + TracedLayer (reference: fluid/dygraph/jit.py:1104)."""

    def test_ema_bias_corrected_apply_restore(self):
        paddle.seed(0)
        lin = nn.Linear(3, 2)
        opt = optim.SGD(learning_rate=0.5, parameters=lin.parameters())
        ema = optim.ExponentialMovingAverage(0.5)
        w_hist = []
        for _ in range(3):
            x = paddle.to_tensor(np.ones((4, 3), np.float32))
            loss = (lin(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            ema.update(lin.parameters())
            w_hist.append(lin.weight.numpy().copy())
        shadow = np.zeros_like(w_hist[0])
        for w in w_hist:
            shadow = 0.5 * shadow + 0.5 * w
        corr = shadow / (1 - 0.5 ** 3)
        w_now = lin.weight.numpy().copy()
        with ema.apply(lin.parameters()):
            np.testing.assert_allclose(lin.weight.numpy(), corr, rtol=1e-5)
        np.testing.assert_allclose(lin.weight.numpy(), w_now)

    def test_traced_layer_matches_eager(self):
        from paddle_tpu.jit import TracedLayer
        paddle.seed(1)
        lin = nn.Linear(3, 2)
        lin.eval()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3).astype(np.float32))
        out, traced = TracedLayer.trace(lin, [x])
        np.testing.assert_allclose(out.numpy(), lin(x).numpy(), rtol=1e-6)
        np.testing.assert_allclose(traced([x]).numpy(), lin(x).numpy(),
                                   rtol=1e-6)


def test_static_nn_dynamic_rnn():
    """Functional DynamicRNN analog (reference:
    fluid/layers/control_flow.py DynamicRNN) — masked tail + frozen
    states, matches nn.RNN on full-length rows."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.static as static
    import paddle_tpu.nn as nn

    paddle.seed(0)
    cell = nn.SimpleRNNCell(3, 5)
    x = np.random.RandomState(0).randn(2, 4, 3).astype(np.float32)
    h0 = paddle.to_tensor(np.zeros((2, 5), np.float32))

    def step(x_t, h):
        o, h2 = cell(x_t, h)
        return o, h2

    outs, last = static.nn.dynamic_rnn(
        step, paddle.to_tensor(x), h0,
        lengths=paddle.to_tensor(np.array([4, 2])))
    o = outs.numpy()
    assert np.abs(o[1, 2:]).max() == 0.0       # padded tail masked
    ref, _ = nn.RNN(cell)(paddle.to_tensor(x))
    np.testing.assert_allclose(o[0], ref.numpy()[0], rtol=1e-5)
    # frozen state: last state of row 1 == its t=2 output
    np.testing.assert_allclose(last.numpy()[1], o[1, 1], rtol=1e-5)


def test_static_save_load_roundtrip_params():
    """static.save/load persist and restore the Program's ACTUAL
    parameter values (round-5 review: the first cut pickled {})."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.static as static

    paddle.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 4], "float32")
            y = static.nn.fc(x, 3)
        params = prog.all_parameters()
        assert params, "fc must register parameters on the program"
        import tempfile, os
        d = tempfile.mkdtemp()
        path = os.path.join(d, "prog")
        before = [p.numpy().copy() for p in params]
        static.save(prog, path)
        for p in params:
            p._data = p._data * 0.0
        state = static.load(prog, path)
        assert state
        for p, b in zip(params, before):
            np.testing.assert_allclose(p.numpy(), b)
    finally:
        paddle.disable_static()


def test_jit_verbosity_knobs(capsys):
    import paddle_tpu as paddle
    from paddle_tpu import jit

    def f(x):
        if x.mean() > 0:
            y = x + 1.0
        else:
            y = x - 1.0
        return y
    jit.set_verbosity(1)
    jit.set_code_level(100)
    try:
        import numpy as np
        jit.to_static(f)(paddle.to_tensor(np.ones(2, np.float32)))
        outp = capsys.readouterr().out
        assert "converted" in outp and "__pt_if__" in outp
    finally:
        jit.set_verbosity(0)
        jit.set_code_level(-1)
