"""Flash attention is load-bearing (round-5): MultiHeadAttention routes
eligible calls to the Pallas kernel, GPT uses it through the CAUSAL_MASK
sentinel, and the two long-context mechanisms (flash kernel, ring
attention SP) agree numerically. Kernel numerics themselves are pinned in
test_flash_attention.py; this file pins the WIRING."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn.transformer import CAUSAL_MASK, FLASH_CROSSOVER


def _mha(attn_impl, dropout=0.0, need_weights=False):
    paddle.seed(11)
    return nn.MultiHeadAttention(32, 4, dropout=dropout,
                                 need_weights=need_weights,
                                 attn_impl=attn_impl)


def _x(b=2, s=24, e=32, seed=0):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randn(b, s, e).astype(np.float32) * 0.3)


class TestMhaRouting:
    def test_flash_forced_matches_dense(self):
        x = _x()
        dense = _mha("dense")
        flash = _mha("flash")
        flash.set_state_dict(dense.state_dict())
        np.testing.assert_allclose(flash(x).numpy(), dense(x).numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_flash_causal_sentinel_matches_dense_triu(self):
        x = _x(seed=1)
        dense = _mha("dense")
        flash = _mha("flash")
        flash.set_state_dict(dense.state_dict())
        np.testing.assert_allclose(
            flash(x, attn_mask=CAUSAL_MASK).numpy(),
            dense(x, attn_mask=CAUSAL_MASK).numpy(),
            rtol=1e-4, atol=1e-5)

    def test_auto_selects_by_crossover(self):
        m = _mha("auto")
        assert not m._flash_eligible(None, None, FLASH_CROSSOVER - 1)
        assert m._flash_eligible(None, None, FLASH_CROSSOVER)
        assert m._flash_eligible(CAUSAL_MASK, None, FLASH_CROSSOVER)

    def test_ineligible_calls_stay_dense(self):
        long = FLASH_CROSSOVER + 64
        # explicit additive mask -> dense
        assert not _mha("flash")._flash_eligible(
            paddle.to_tensor(np.zeros((4, 4), np.float32)), None, long)
        # attention dropout in training mode -> dense
        m = _mha("flash", dropout=0.1)
        m.train()
        assert not m._flash_eligible(None, None, long)
        m.eval()
        assert m._flash_eligible(None, None, long)
        # need_weights (prob matrix must materialise) -> dense
        assert not _mha("flash", need_weights=True)._flash_eligible(
            None, None, long)
        # incremental decode cache -> dense
        m2 = _mha("flash")
        cache = m2.gen_cache(_x())
        assert not m2._flash_eligible(None, cache, long)

    def test_grad_flash_matches_dense(self):
        xd, xf = _x(seed=2), _x(seed=2)
        xd.stop_gradient = False
        xf.stop_gradient = False
        dense = _mha("dense")
        flash = _mha("flash")
        flash.set_state_dict(dense.state_dict())
        dense(xd, attn_mask=CAUSAL_MASK).sum().backward()
        flash(xf, attn_mask=CAUSAL_MASK).sum().backward()
        np.testing.assert_allclose(xf.grad.numpy(), xd.grad.numpy(),
                                   rtol=1e-3, atol=1e-5)


class TestGptFlash:
    def _cfg(self, attn_impl):
        from paddle_tpu.models import GPTConfig
        return GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                         num_heads=4, max_position_embeddings=64,
                         hidden_dropout_prob=0.0,
                         attention_dropout_prob=0.0, attn_impl=attn_impl)

    def test_gpt_flash_equals_dense(self):
        from paddle_tpu.models import GPTForCausalLM
        paddle.seed(5)
        dense = GPTForCausalLM(self._cfg("dense"))
        paddle.seed(5)
        flash = GPTForCausalLM(self._cfg("flash"))
        flash.set_state_dict(dense.state_dict())
        dense.eval()
        flash.eval()
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 128, (2, 24)).astype(np.int32))
        np.testing.assert_allclose(flash(ids).numpy(), dense(ids).numpy(),
                                   rtol=2e-3, atol=2e-4)

    def test_gpt_flash_trains(self):
        from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
        import paddle_tpu.optimizer as optim
        paddle.seed(6)
        net = GPTForCausalLM(self._cfg("flash"))
        m = paddle.Model(net)
        m.prepare(optim.AdamW(learning_rate=1e-3,
                              parameters=net.parameters()),
                  GPTPretrainingCriterion())
        ids = np.random.RandomState(1).randint(0, 128, (2, 24))
        losses = [m.train_batch([paddle.to_tensor(ids.astype(np.int32))],
                                [paddle.to_tensor(ids.astype(np.int64))])[0]
                  for _ in range(6)]
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestFlashRingComposition:
    def test_flash_single_chip_equals_ring_sharded(self):
        """The two long-context mechanisms must agree: full-sequence flash
        attention on one device == ring attention with the sequence dim
        sharded over an sp mesh (both causal)."""
        import jax
        import jax.numpy as jnp
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.sequence_parallel import (
            ring_attention)
        from paddle_tpu.ops.pallas_attention import flash_attention

        rng = np.random.RandomState(3)
        B, H, S, D = 1, 2, 64, 16
        q = rng.randn(B, S, H, D).astype(np.float32) * 0.4
        k = rng.randn(B, S, H, D).astype(np.float32) * 0.4
        v = rng.randn(B, S, H, D).astype(np.float32)

        out_flash, _ = flash_attention(paddle.to_tensor(q),
                                       paddle.to_tensor(k),
                                       paddle.to_tensor(v), causal=True)

        mesh = dist.build_mesh({"sp": 8})
        dist.set_mesh(mesh)
        try:
            bhsd = lambda a: jnp.moveaxis(jnp.asarray(a), 2, 1)  # BSHD->BHSD
            out_ring = ring_attention(bhsd(q), bhsd(k), bhsd(v),
                                      mesh=mesh, axis="sp", causal=True)
            out_ring = np.moveaxis(np.asarray(out_ring), 1, 2)
        finally:
            dist.set_mesh(None)
        np.testing.assert_allclose(out_flash.numpy(), out_ring,
                                   rtol=1e-4, atol=1e-5)


class TestRingFlashComposition:
    """ring_flash_attention: the Pallas kernel as the per-chunk compute
    INSIDE the sequence-parallel ring (lse-merge across chunks) — the
    full composition, not just the equivalence pin above."""

    def _qkv(self, B, H, T, D, seed=0):
        import jax.numpy as jnp
        rng = np.random.RandomState(seed)
        return (jnp.asarray(rng.randn(B, H, T, D), jnp.float32) * 0.4,
                jnp.asarray(rng.randn(B, H, T, D), jnp.float32) * 0.4,
                jnp.asarray(rng.randn(B, H, T, D), jnp.float32))

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_ring(self, causal):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.sequence_parallel import (
            ring_attention, ring_flash_attention)
        mesh = dist.build_mesh({"sp": 8})
        dist.set_mesh(mesh)
        try:
            q, k, v = self._qkv(1, 2, 128, 16)
            ref = np.asarray(ring_attention(q, k, v, mesh=mesh,
                                            causal=causal))
            got = np.asarray(ring_flash_attention(q, k, v, mesh=mesh,
                                                  causal=causal))
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        finally:
            dist.set_mesh(None)

    def test_under_jit_with_dp(self):
        import jax
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.sequence_parallel import (
            ring_attention, ring_flash_attention)
        mesh = dist.build_mesh({"dp": 2, "sp": 4})
        dist.set_mesh(mesh)
        try:
            q, k, v = self._qkv(2, 2, 64, 16, seed=1)

            @jax.jit
            def f(q, k, v):
                return ring_flash_attention(q, k, v, mesh=mesh,
                                            causal=True,
                                            batch_axes="dp")
            got = np.asarray(f(q, k, v))
            ref = np.asarray(ring_attention(q, k, v, mesh=mesh,
                                            causal=True,
                                            batch_axes="dp"))
            np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        finally:
            dist.set_mesh(None)

    def test_shard_size_constraint(self):
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.fleet.sequence_parallel import (
            ring_flash_attention)
        mesh = dist.build_mesh({"sp": 8})
        dist.set_mesh(mesh)
        try:
            q, k, v = self._qkv(1, 1, 40, 16)   # Tl=5: not 16-multiple
            with pytest.raises(Exception, match="multiple of 16"):
                np.asarray(ring_flash_attention(q, k, v, mesh=mesh))
        finally:
            dist.set_mesh(None)


def test_ring_attention_wrapper_use_flash():
    import jax.numpy as jnp
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.sequence_parallel import RingAttention
    mesh = dist.build_mesh({"sp": 8})
    dist.set_mesh(mesh)
    try:
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.randn(1, 2, 128, 16), jnp.float32) * 0.4
        dense = RingAttention(causal=True)(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q))
        flash = RingAttention(causal=True, use_flash=True)(
            paddle.to_tensor(q), paddle.to_tensor(q), paddle.to_tensor(q))
        np.testing.assert_allclose(flash.numpy(), dense.numpy(),
                                   rtol=1e-5, atol=1e-6)
    finally:
        dist.set_mesh(None)


def test_ring_flash_grad_through_wrapper():
    """RingAttention(use_flash=True) is trainable: backprop through the
    op-funnel tape reaches the ring-flash custom_vjp backward and matches
    the dense-ring path's gradients (tests/test_ring_flash_backward.py
    covers the raw-jax surface exhaustively)."""
    import jax.numpy as jnp
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.sequence_parallel import RingAttention
    mesh = dist.build_mesh({"sp": 8})
    dist.set_mesh(mesh)
    try:
        rng = np.random.RandomState(5)
        q_np = rng.randn(1, 2, 128, 16).astype(np.float32) * 0.3

        def grads(use_flash):
            q = paddle.to_tensor(q_np.copy(), stop_gradient=False)
            out = RingAttention(causal=True, use_flash=use_flash)(q, q, q)
            out.sum().backward()
            return q.grad.numpy()

        gd, gf = grads(False), grads(True)
        assert np.all(np.isfinite(gf))
        assert np.any(gf != 0.0)
        np.testing.assert_allclose(gf, gd, rtol=2e-4, atol=2e-5)
    finally:
        dist.set_mesh(None)


def test_gpt_generate_greedy_and_sampling():
    """GPTForCausalLM.generate (PaddleNLP GenerationMixin capability):
    greedy is deterministic and equals stepwise argmax; sampling with
    top_k stays in the top-k support."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    import jax.numpy as jnp
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=64,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    ids = paddle.to_tensor(np.array([[1, 2, 3]], np.int32))
    out = net.generate(ids, max_length=4)
    assert tuple(out.shape) == (1, 7)
    # greedy equals manual stepwise argmax
    cur = ids.numpy()
    for _ in range(4):
        logits = net(paddle.to_tensor(cur.astype(np.int32))).numpy()
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out.numpy(), cur)
    paddle.seed(3)
    s = net.generate(ids, max_length=4, decode_strategy="sampling",
                     top_k=5)
    assert tuple(s.shape) == (1, 7)
    # every sampled token lies in the stepwise top-5 of the true logits
    sn = s.numpy()
    for t in range(3, 7):
        logits = net(paddle.to_tensor(sn[:, :t].astype(np.int32))).numpy()
        top5 = np.argsort(-logits[0, -1])[:5]
        assert sn[0, t] in top5, (t, sn[0, t], top5)
    with pytest.raises(ValueError, match="decode_strategy"):
        net.generate(ids, max_length=2, decode_strategy="beam")


def test_gpt_generate_per_row_eos_freeze():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(1)
    cfg = GPTConfig(vocab_size=16, hidden_size=16, num_layers=1,
                    num_heads=2, max_position_embeddings=64,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int32))
    # find a token some row emits greedily, then use it as eos
    first = net.generate(ids, max_length=1).numpy()[:, -1]
    eos = int(first[0])
    out = net.generate(ids, max_length=6, eos_token_id=eos).numpy()
    # row 0 hit eos at step 1: every later token must stay eos
    row0 = out[0, 2:]
    hit = np.where(row0 == eos)[0]
    assert hit.size and (row0[hit[0]:] == eos).all()


def test_gpt_generate_kv_cache_equals_recompute():
    """use_cache=True (incremental KV decoding through the MHA cache +
    position offsets) must be token-identical to full-prefix recompute."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(2)
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                    num_heads=2, max_position_embeddings=64,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    ids = paddle.to_tensor(np.array([[1, 2, 3], [4, 5, 6]], np.int32))
    slow = net.generate(ids, max_length=6, use_cache=False).numpy()
    fast = net.generate(ids, max_length=6, use_cache=True).numpy()
    np.testing.assert_array_equal(slow, fast)
    # cached forward returns (logits, new_cache) and grows the cache
    cache = net.gpt.gen_cache(ids)
    logits, cache = net(ids, cache=cache)
    assert tuple(logits.shape) == (2, 3, 32)
    assert int(cache[0].k.shape[2]) == 3
    logits2, cache = net(paddle.to_tensor(
        np.array([[7], [8]], np.int32)), cache=cache)
    assert tuple(logits2.shape) == (2, 1, 32)
    assert int(cache[0].k.shape[2]) == 4
