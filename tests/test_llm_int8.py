"""Int8 serving executables: real-int8 GPT weights (per-out-channel
scales fused into the matmuls), the int8 StaticKVCache (per-row scales,
dequant inside the fused decode step), the memory bar that doubles
slots-per-chip, and the engine-config gating."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving.llm import LLMEngine, LLMEngineConfig, StaticKVCache
from paddle_tpu.serving.llm.decode import (
    GPTStaticDecoder, _QUANT_WEIGHT_KEYS, extract_gpt_params,
    quantize_gpt_params)
from paddle_tpu.serving.llm.kvcache import (
    dequantize_kv, is_quantized_kv, kv_layer_view, kv_max_seq,
    quantize_kv_rows)
from paddle_tpu.serving.cache import ExecutableCache


def _tiny_model(seed=0, vocab=64, hidden=32, layers=2, heads=4, max_pos=128):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=max_pos,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0)
    net = GPTForCausalLM(cfg)
    net.eval()
    return net


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


class TestQuantizedWeights:
    def test_quantize_gpt_params_layout(self, model):
        p = extract_gpt_params(model)
        q = quantize_gpt_params(p)
        for key in _QUANT_WEIGHT_KEYS:
            leaf = q["layers"][0][key]
            assert leaf["q"].dtype == jnp.int8
            assert leaf["s"].dtype == jnp.float32
        # embeddings/norms stay f32 (tok doubles as the logit head)
        assert q["tok"].dtype == jnp.float32
        assert q["layers"][0]["n1w"].dtype == jnp.float32

    def test_dequant_matches_quant_matmul(self, model):
        """(x @ q) * s must equal x @ dequantized(w) exactly — the fused
        form is the same arithmetic, reassociated."""
        p = extract_gpt_params(model)
        q = quantize_gpt_params(p)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, p["layers"][0]["qw"].shape[0]
                                             )), jnp.float32)
        lq = q["layers"][0]["qw"]
        fused = (x @ lq["q"].astype(x.dtype)) * lq["s"]
        deq = x @ (lq["q"].astype(jnp.float32) * lq["s"])
        np.testing.assert_allclose(np.asarray(fused), np.asarray(deq),
                                   rtol=1e-5, atol=1e-5)

    def test_weight_memory_halves(self, model):
        p = extract_gpt_params(model)
        q = quantize_gpt_params(p)

        def nbytes(t):
            return sum(x.nbytes for x in jax.tree_util.tree_leaves(t))
        w_dense = sum(nbytes(p["layers"][0][k]) for k in _QUANT_WEIGHT_KEYS)
        w_int8 = sum(nbytes(q["layers"][0][k]) for k in _QUANT_WEIGHT_KEYS)
        assert w_dense / w_int8 > 3.5  # int8 + small scale sidecar


class TestInt8KVCache:
    def test_row_quantize_roundtrip(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((6, 4, 8)), jnp.float32)
        buf = quantize_kv_rows(x)
        assert buf["q"].dtype == jnp.int8 and buf["s"].shape == (6,)
        deq = dequantize_kv(buf)
        absmax = np.abs(np.asarray(x)).reshape(6, -1).max(axis=1)
        bound = absmax[:, None, None] / (2 * 127) + 1e-7
        assert np.all(np.abs(np.asarray(deq - x)) <= bound)

    def test_dense_passthrough(self):
        x = jnp.ones((2, 3, 4))
        assert dequantize_kv(x) is x
        assert not is_quantized_kv(x)

    def test_cache_construction_and_helpers(self):
        kv = StaticKVCache(num_slots=2, num_layers=3, max_seq=8,
                           num_heads=2, head_dim=4, kv_dtype="int8")
        assert kv.quantized
        assert kv.k["q"].shape == (2, 3, 8, 2, 4)
        assert kv.k["s"].shape == (2, 3, 8)
        assert kv_max_seq(kv.k) == 8
        view = kv_layer_view(kv.k, 1)
        assert view["q"].shape == (2, 8, 2, 4)

    def test_bad_kv_dtype_rejected(self):
        with pytest.raises(ValueError):
            StaticKVCache(num_slots=1, num_layers=1, max_seq=4,
                          num_heads=1, head_dim=2, kv_dtype="int4")

    def test_kv_memory_bar(self):
        """slots-per-chip: int8 KV must fit >= 1.8x the sequences of the
        f32 cache in the same byte budget."""
        kw = dict(num_slots=8, num_layers=2, max_seq=64, num_heads=4,
                  head_dim=8)
        dense = StaticKVCache(**kw)
        q = StaticKVCache(**kw, kv_dtype="int8")
        ratio = dense.kv_bytes() / q.kv_bytes()
        assert ratio >= 1.8, ratio

    def test_prefix_export_gated(self):
        kv = StaticKVCache(num_slots=1, num_layers=1, max_seq=4,
                           num_heads=1, head_dim=2, kv_dtype="int8")
        with pytest.raises(NotImplementedError):
            kv.host_slot_kv(0, 2)


class TestInt8Decode:
    def test_logits_close_to_f32(self, model):
        """One decode step, identical state: int8 logits must stay within
        a few percent of f32 (relative to the logit range)."""
        from paddle_tpu.serving.llm.decode import (GPTDecodeSpec,
                                                   build_decode_step)
        spec = GPTDecodeSpec.from_model(model)
        p = extract_gpt_params(model)
        slots, max_seq = 2, 16
        kv_shape = (slots, spec.num_layers, max_seq, spec.num_heads,
                    spec.head_dim)
        rng = np.random.default_rng(2)
        kf = jnp.asarray(rng.standard_normal(kv_shape) * 0.3, jnp.float32)
        vf = jnp.asarray(rng.standard_normal(kv_shape) * 0.3, jnp.float32)
        common = (jnp.asarray([3, 1], jnp.int32), jnp.zeros((slots,), bool),
                  jnp.asarray([5, 7], jnp.int32),
                  jnp.ones((slots,), jnp.float32),
                  jnp.zeros((slots,), jnp.int32), jnp.zeros((slots,), bool),
                  jnp.full((slots,), -1, jnp.int32), jax.random.PRNGKey(0))
        step = jax.jit(build_decode_step(spec, 4))
        out_f = step(p, kf, vf, *common)

        def q_kv(x):
            flat = x.reshape(-1, spec.num_heads, spec.head_dim)
            b = quantize_kv_rows(flat)
            return {"q": b["q"].reshape(kv_shape),
                    "s": b["s"].reshape(kv_shape[:3])}
        out_q = step(quantize_gpt_params(p), q_kv(kf), q_kv(vf), *common)
        # compare the hidden-state-derived next tokens' source: rerun the
        # step's logits path indirectly via the sampled greedy tokens of
        # both runs being drawn from near-identical logits. The direct
        # check: updated KV rows decode to close values.
        kd_f = np.asarray(out_f[0])
        kd_q = np.asarray(dequantize_kv(out_q[0]))
        err = np.abs(kd_f - kd_q).max()
        scale = np.abs(kd_f).max() + 1e-6
        assert err / scale < 0.02, err / scale

    def test_decoder_end_to_end_greedy(self, model):
        """Full decoder objects: prefill + 6 greedy decode steps; int8
        output must be a plausible continuation (valid token ids) and the
        KV cache must stay int8 throughout; warm recompiles == 0."""
        cache = ExecutableCache()
        dec = GPTStaticDecoder(model, max_top_k=8, exec_cache=cache,
                               weight_dtype="int8", kv_dtype="int8")
        assert dec.weight_dtype == "int8"
        params = dec.params()
        assert params["layers"][0]["qw"]["q"].dtype == jnp.int8
        kv = dec.new_kv(num_slots=2, max_seq=32)
        assert kv.quantized

        from paddle_tpu.serving.llm.decode import (SamplingParams,
                                                   pack_sampling)
        samp = pack_sampling([SamplingParams(), SamplingParams()])
        finished = jnp.zeros((2,), bool)
        toks = jnp.asarray([[5, 9, 2, 11], [3, 1, 4, 1]], jnp.int32)
        kv.alloc(), kv.alloc()
        key = jax.random.PRNGKey(0)
        nxt, finished = dec.prefill(kv, params, toks,
                                    jnp.asarray([4, 4], jnp.int32),
                                    jnp.asarray([0, 1], jnp.int32),
                                    finished, samp, key)
        seq = [np.asarray(nxt)]
        for i in range(6):
            nxt, finished = dec.decode_step(kv, params, finished, nxt, samp,
                                            jax.random.PRNGKey(i + 1))
            seq.append(np.asarray(nxt))
        toks_out = np.stack(seq)
        assert toks_out.min() >= 0 and toks_out.max() < dec.spec.vocab_size
        assert is_quantized_kv(kv.k)
        # warm path: all six decode steps share one executable
        fn = dec.decode_fn(2, 32)
        assert fn.trace_counter["traces"] == 1

    def test_prefix_paths_gated(self, model):
        dec = GPTStaticDecoder(model, kv_dtype="int8")
        kv = dec.new_kv(num_slots=1, max_seq=16)
        with pytest.raises(NotImplementedError):
            dec.insert_prefix(kv, np.zeros((2, 4, 4, 8), np.float32),
                              np.zeros((2, 4, 4, 8), np.float32), 0)
        with pytest.raises(NotImplementedError):
            dec.tail_prefill(kv, dec.params(), None, None, None, None,
                             None, None, None)

    def test_bad_dtypes_rejected(self, model):
        with pytest.raises(ValueError):
            GPTStaticDecoder(model, weight_dtype="fp8")
        with pytest.raises(ValueError):
            GPTStaticDecoder(model, kv_dtype="int4")

    def test_cache_keys_do_not_collide(self, model):
        cache = ExecutableCache()
        d32 = GPTStaticDecoder(model, exec_cache=cache)
        d8 = GPTStaticDecoder(model, exec_cache=cache,
                              weight_dtype="int8", kv_dtype="int8")
        assert d32._key != d8._key


class TestEngineConfig:
    def test_int8_flags_validated(self):
        with pytest.raises(ValueError, match="prefix_cache"):
            LLMEngineConfig(kv_dtype="int8", prefix_cache=True)
        with pytest.raises(ValueError, match="spec"):
            LLMEngineConfig(kv_dtype="int8", spec_k=2)
        with pytest.raises(ValueError):
            LLMEngineConfig(weight_dtype="bf4")
        cfg = LLMEngineConfig(weight_dtype="int8", kv_dtype="int8")
        assert cfg.weight_dtype == "int8" and cfg.kv_dtype == "int8"

    def test_engine_generates_int8(self, model):
        eng = LLMEngine(model, LLMEngineConfig(
            num_slots=2, max_seq=32, prefill_buckets=(8,), warmup=False,
            weight_dtype="int8", kv_dtype="int8"))
        try:
            out = eng.submit([5, 9, 2], max_new_tokens=4).result(timeout=120)
            assert len(out["tokens"]) == 4
            assert eng._batcher.kv.quantized
        finally:
            eng.drain(timeout=60)

    def test_shared_prefix_store_rejected(self, model):
        from paddle_tpu.serving.llm.prefix import PrefixStore
        store = PrefixStore(capacity_bytes=1 << 20, block_tokens=8)
        with pytest.raises(ValueError, match="dense KV"):
            LLMEngine(model, LLMEngineConfig(
                num_slots=2, max_seq=32, prefill_buckets=(8,),
                warmup=False, kv_dtype="int8"), prefix_store=store)
