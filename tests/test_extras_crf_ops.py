"""Numpy-reference tests for the round-4 extras batch and the CRF family.

Pins: bpr_loss_op.h:70, modified_huber_loss_op.h:43,
teacher_student_sigmoid_loss_op.h:34, center_loss_op.cc, mean_iou_op.cc,
row_conv_op.cc, conv_shift_op.cc, fsp_op.cc, cvm_op.cc, data_norm_op.cc:302,
linear_chain_crf_op.h (brute-force partition check), crf_decoding_op.h,
chunk_eval_op.h.
"""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import ops

t = paddle.to_tensor


# -- small losses -------------------------------------------------------------

def test_bpr_loss_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6).astype(np.float32)
    lab = np.array([2, 0, 5, 1])
    got = ops.bpr_loss(t(x), t(lab)).numpy()
    exp = np.zeros((4, 1))
    for i in range(4):
        s = 0.0
        for j in range(6):
            if j == lab[i]:
                continue
            s += -np.log(1.0 / (1.0 + np.exp(x[i, j] - x[i, lab[i]])))
        exp[i, 0] = s / 5
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_modified_huber_loss_numpy():
    x = np.array([-2.0, -0.5, 0.3, 2.0], np.float32)
    y = np.array([1.0, 0.0, 1.0, 1.0], np.float32)
    got = ops.modified_huber_loss(t(x), t(y)).numpy()
    inter = x * (2 * y - 1)
    exp = np.where(inter < -1, -4 * inter,
                   np.where(inter < 1, (1 - inter) ** 2, 0))
    np.testing.assert_allclose(got, exp, rtol=1e-5)


def test_teacher_student_sigmoid_loss_cases():
    x = np.array([0.5, 0.5, 0.5, 0.5], np.float32)
    lab = np.array([-2.0, -1.0, 0.3, 1.7], np.float32)
    got = ops.teacher_student_sigmoid_loss(t(x), t(lab)).numpy().ravel()

    def part(xx, z):
        return max(xx, 0) - xx * z + np.log1p(np.exp(-abs(xx)))
    exp = np.array([part(0.5, 0), part(0.5, 1),
                    part(0.5, 0) + part(0.5, 0.3),
                    part(0.5, 1) + part(0.5, 0.7)])
    np.testing.assert_allclose(got, exp, rtol=1e-5)


def test_center_loss_numpy():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 3).astype(np.float32)
    centers = rng.randn(5, 3).astype(np.float32)
    lab = np.array([1, 1, 0, 3])
    loss, new_c = ops.center_loss(t(x), t(lab), t(centers), alpha=0.5)
    exp_loss = 0.5 * ((x - centers[lab]) ** 2).sum(1, keepdims=True)
    np.testing.assert_allclose(loss.numpy(), exp_loss, rtol=1e-4)
    # class-1 center moved toward the mean of its two samples
    diff = (x[0] - centers[1]) + (x[1] - centers[1])
    exp_c1 = centers[1] - 0.5 * diff / 3.0          # (1 + count) normalizer
    np.testing.assert_allclose(new_c.numpy()[1], exp_c1, rtol=1e-4)
    # untouched class keeps its center
    np.testing.assert_allclose(new_c.numpy()[2], centers[2], rtol=1e-6)


def test_margin_rank_loss():
    lab = np.array([1.0, -1.0], np.float32)
    left = np.array([0.5, 0.5], np.float32)
    right = np.array([0.3, 0.3], np.float32)
    got = ops.margin_rank_loss(t(lab), t(left), t(right), margin=0.1).numpy()
    np.testing.assert_allclose(got, np.maximum(0, -lab * (left - right) + 0.1),
                               rtol=1e-5)


def test_squared_l2_distance():
    rng = np.random.RandomState(2)
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32)
    got = ops.squared_l2_distance(t(x), t(y)).numpy()
    np.testing.assert_allclose(got, ((x - y) ** 2).sum(1, keepdims=True),
                               rtol=1e-4)


# -- metrics ------------------------------------------------------------------

def test_mean_iou_numpy():
    pred = np.array([0, 0, 1, 1, 2, 2])
    lab = np.array([0, 1, 1, 1, 2, 0])
    miou, wrong, correct = ops.mean_iou(t(pred), t(lab), 3)
    # per class: c0 TP1 FP1 FN1 iou 1/3; c1 TP2 FP0 FN1 iou 2/3; c2 TP1 FP1 FN0 iou 1/2
    np.testing.assert_allclose(miou.numpy(), (1 / 3 + 2 / 3 + 1 / 2) / 3,
                               rtol=1e-5)
    np.testing.assert_array_equal(correct.numpy(), [1, 2, 1])


def test_precision_recall_micro_macro():
    pred = np.array([0, 1, 1, 0])
    lab = np.array([0, 1, 0, 0])
    out = ops.precision_recall(t(pred), t(lab), 2).numpy()
    # c0: tp2 fp0 fn1 -> P1 R2/3; c1: tp1 fp1 fn0 -> P.5 R1
    assert abs(out[0] - (1.0 + 0.5) / 2) < 1e-5          # macro P
    assert abs(out[3] - 3 / 4) < 1e-5                     # micro P
    assert abs(out[4] - 3 / 4) < 1e-5                     # micro R


def test_positive_negative_pair_queries():
    score = np.array([3.0, 1.0, 2.0, 2.0], np.float32)
    lab = np.array([2.0, 1.0, 1.0, 2.0], np.float32)
    q = np.array([0, 0, 0, 1])
    pos, neg, neu = ops.positive_negative_pair(t(score), t(lab), t(q))
    # query0: (0 vs 1): 3>1 pos; (0 vs 2): 3>2 pos. query1 alone: none.
    assert float(pos.numpy()) == 2 and float(neg.numpy()) == 0
    assert float(neu.numpy()) == 0


# -- feature ops --------------------------------------------------------------

def test_affine_channel_and_data_norm():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 2, 2).astype(np.float32)
    s = np.array([1.0, 2.0, 3.0], np.float32)
    b = np.array([0.5, 0.0, -1.0], np.float32)
    got = ops.affine_channel(t(x), t(s), t(b)).numpy()
    np.testing.assert_allclose(got, x * s[None, :, None, None]
                               + b[None, :, None, None], rtol=1e-5)

    xd = rng.randn(4, 3).astype(np.float32)
    bs = np.full(3, 8.0, np.float32)
    bsum = rng.randn(3).astype(np.float32)
    bsq = np.abs(rng.randn(3)).astype(np.float32) + 1
    y, means, scales = ops.data_norm(t(xd), t(bs), t(bsum), t(bsq))
    np.testing.assert_allclose(means.numpy(), bsum / bs, rtol=1e-5)
    np.testing.assert_allclose(scales.numpy(), np.sqrt(bs / bsq), rtol=1e-5)
    np.testing.assert_allclose(
        y.numpy(), (xd - (bsum / bs)[None]) * np.sqrt(bs / bsq)[None],
        rtol=1e-4)


def test_cvm_partial_shuffle():
    x = np.abs(np.random.RandomState(4).randn(3, 5)).astype(np.float32)
    got = ops.cvm(t(x), use_cvm=True).numpy()
    np.testing.assert_allclose(got[:, 0], np.log(x[:, 0] + 1), rtol=1e-5)
    np.testing.assert_allclose(got[:, 1],
                               np.log(x[:, 1] + 1) - np.log(x[:, 0] + 1),
                               rtol=1e-4, atol=1e-6)
    assert ops.cvm(t(x), use_cvm=False).shape == [3, 3]

    a = np.arange(12, dtype=np.float32).reshape(2, 6)
    np.testing.assert_allclose(
        ops.partial_concat([t(a), t(a)], 1, 2).numpy(),
        np.concatenate([a[:, 1:3], a[:, 1:3]], 1))
    np.testing.assert_allclose(ops.partial_sum([t(a), t(a)], 0, 3).numpy(),
                               2 * a[:, :3])

    s, idx = ops.shuffle_batch(t(a), seed=7)
    np.testing.assert_allclose(s.numpy(), a[idx.numpy()])


def test_filter_by_instag_mask():
    x = np.arange(8, dtype=np.float32).reshape(4, 2)
    tags = np.array([[1, 0], [2, 0], [3, 1], [9, 9]])
    out, mask, lw = ops.filter_by_instag(t(x), t(tags), t(np.array([1, 3])))
    np.testing.assert_array_equal(mask.numpy(), [True, False, True, False])
    np.testing.assert_allclose(out.numpy()[1], 0)
    np.testing.assert_allclose(out.numpy()[0], x[0])


# -- NN misc ------------------------------------------------------------------

def test_row_conv_numpy():
    rng = np.random.RandomState(5)
    x = rng.randn(2, 6, 3).astype(np.float32)
    w = rng.randn(3, 3).astype(np.float32)   # ctx=3
    got = ops.row_conv(t(x), t(w)).numpy()
    exp = np.zeros_like(x)
    for b in range(2):
        for i in range(6):
            for j in range(3):
                if i + j < 6:
                    exp[b, i] += x[b, i + j] * w[j]
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_conv_shift_numpy():
    rng = np.random.RandomState(6)
    x = rng.randn(2, 7).astype(np.float32)
    y = rng.randn(2, 3).astype(np.float32)
    got = ops.conv_shift(t(x), t(y)).numpy()
    exp = np.zeros_like(x)
    for b in range(2):
        for i in range(7):
            for j in range(3):
                exp[b, i] += x[b, (i + j - 1) % 7] * y[b, j]
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_fsp_numpy():
    rng = np.random.RandomState(7)
    x = rng.randn(2, 3, 4, 5).astype(np.float32)
    y = rng.randn(2, 6, 4, 5).astype(np.float32)
    got = ops.fsp(t(x), t(y)).numpy()
    exp = np.einsum("bihw,bjhw->bij", x, y) / 20
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_spp_divisible_matches_manual():
    rng = np.random.RandomState(8)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    got = ops.spp(t(x), 3, "max").numpy()
    assert got.shape == (2, 3 * (1 + 4 + 16))
    # level 0 is the global max
    np.testing.assert_allclose(got[:, :3], x.max((2, 3)), rtol=1e-5)
    # level 2: 4x4 grid of 2x2 maxes
    lvl2 = x.reshape(2, 3, 4, 2, 4, 2).max((3, 5)).reshape(2, -1)
    np.testing.assert_allclose(got[:, 15:], lvl2, rtol=1e-5)


def test_max_unpool2d_roundtrip():
    import paddle_tpu.nn.functional as F
    rng = np.random.RandomState(9)
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    pooled, mask = F.max_pool2d(t(x), 2, return_mask=True)
    up = ops.max_unpool2d(pooled, mask, 2).numpy()
    assert up.shape == (1, 2, 6, 6)
    # every pooled max lands back at its argmax position
    np.testing.assert_allclose(np.sort(up[up != 0]),
                               np.sort(pooled.numpy().ravel()))


def test_add_position_encoding_alpha_beta():
    x = np.zeros((1, 4, 6), np.float32)
    got = ops.add_position_encoding(t(x), alpha=2.0, beta=1.0).numpy()
    # position 0: sin(0)=0 for first half, cos(0)=1 for second
    np.testing.assert_allclose(got[0, 0, :3], 0, atol=1e-6)
    np.testing.assert_allclose(got[0, 0, 3:], 1, atol=1e-6)
    got2 = ops.add_position_encoding(t(np.ones((1, 4, 6), np.float32)),
                                     alpha=2.0, beta=0.0).numpy()
    np.testing.assert_allclose(got2, 2.0, atol=1e-6)


def test_correlation_zero_displacement_is_mean_product():
    rng = np.random.RandomState(10)
    a = rng.randn(1, 4, 5, 5).astype(np.float32)
    b = rng.randn(1, 4, 5, 5).astype(np.float32)
    out = ops.correlation(t(a), t(b), pad_size=1, kernel_size=1,
                          max_displacement=1, stride1=1, stride2=1).numpy()
    assert out.shape == (1, 9, 5, 5)
    np.testing.assert_allclose(out[0, 4], (a * b).mean(1)[0], rtol=1e-4,
                               atol=1e-5)


def test_similarity_focus_exclusive_mask():
    x = np.zeros((1, 2, 3, 3), np.float32)
    x[0, 0] = [[9, 1, 1], [1, 5, 1], [1, 1, 7]]
    got = ops.similarity_focus(t(x), 1, [0]).numpy()
    # greedy: (0,0)=9, then (2,2)=7, then (1,1)=5 — the diagonal
    np.testing.assert_allclose(got[0, 0], np.eye(3), atol=1e-6)
    np.testing.assert_allclose(got[0, 1], np.eye(3), atol=1e-6)


def test_match_matrix_tensor_numpy():
    rng = np.random.RandomState(11)
    x = rng.randn(2, 3, 4).astype(np.float32)
    y = rng.randn(2, 5, 4).astype(np.float32)
    w = rng.randn(4, 2, 4).astype(np.float32)
    got = ops.match_matrix_tensor(t(x), t(y), t(w)).numpy()
    exp = np.einsum("bid,dte,bje->btij", x, w, y)
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)
    # masked version zeroes padding
    got2 = ops.match_matrix_tensor(
        t(x), t(y), t(w), t(np.array([2, 3])), t(np.array([5, 1]))).numpy()
    assert np.all(got2[0, :, 2:, :] == 0)
    assert np.all(got2[1, :, :, 1:] == 0)


# -- tensor utilities ---------------------------------------------------------

def test_shape_size_isfinite():
    x = np.array([[1.0, np.inf], [np.nan, 2.0]], np.float32)
    np.testing.assert_array_equal(ops.shape(t(x)).numpy(), [2, 2])
    assert int(ops.size(t(x)).numpy()) == 4
    np.testing.assert_array_equal(ops.isfinite(t(x)).numpy(),
                                  [[True, False], [False, True]])
    np.testing.assert_array_equal(ops.isinf(t(x)).numpy(),
                                  [[False, True], [False, False]])
    np.testing.assert_array_equal(ops.isnan(t(x)).numpy(),
                                  [[False, False], [True, False]])


def test_batch_size_like_and_pad_constant_like():
    ref = np.zeros((5, 2), np.float32)
    out = ops.fill_constant_batch_size_like(t(ref), [0, 7], "float32", 3.5)
    assert out.shape == [5, 7] and float(out.numpy()[0, 0]) == 3.5
    u = ops.uniform_random_batch_size_like(t(ref), [0, 4], low=0, high=1)
    assert u.shape == [5, 4]
    g = ops.gaussian_random_batch_size_like(t(ref), [0, 3])
    assert g.shape == [5, 3]
    x = np.ones((4, 5), np.float32)
    y = np.ones((2, 3), np.float32) * 2
    p = ops.pad_constant_like(t(x), t(y), pad_value=-1.0).numpy()
    assert p.shape == (4, 5)
    np.testing.assert_allclose(p[:2, :3], 2.0)
    np.testing.assert_allclose(p[2:, :], -1.0)


def test_batch_fc():
    rng = np.random.RandomState(12)
    x = rng.randn(2, 3, 4).astype(np.float32)
    w = rng.randn(2, 4, 5).astype(np.float32)
    b = rng.randn(2, 1, 5).astype(np.float32)
    got = ops.batch_fc(t(x), t(w), t(b)).numpy()
    np.testing.assert_allclose(got, np.einsum("snd,sdo->sno", x, w) + b,
                               rtol=1e-4, atol=1e-5)


# -- CRF ----------------------------------------------------------------------

def _brute_crf(em, tr, lab, L):
    """Enumerate all paths: returns (nll, best_path)."""
    C = em.shape[1]
    start, stop, W = tr[0], tr[1], tr[2:]

    def score(path):
        s = start[path[0]] + em[0, path[0]] + stop[path[L - 1]]
        for k in range(1, L):
            s += em[k, path[k]] + W[path[k - 1], path[k]]
        return s
    paths = list(itertools.product(range(C), repeat=L))
    scores = np.array([score(p) for p in paths])
    logZ = np.log(np.sum(np.exp(scores - scores.max()))) + scores.max()
    nll = logZ - score(lab[:L])
    return nll, np.array(paths[int(np.argmax(scores))])


def test_linear_chain_crf_brute_force():
    rng = np.random.RandomState(13)
    N, T, C = 3, 4, 3
    em = rng.randn(N, T, C).astype(np.float32)
    tr = rng.randn(C + 2, C).astype(np.float32)
    lab = rng.randint(0, C, (N, T)).astype(np.int64)
    lens = np.array([4, 2, 3])
    got = ops.linear_chain_crf(t(em), t(tr), t(lab), t(lens)).numpy()
    for n in range(N):
        nll, _ = _brute_crf(em[n], tr, lab[n], int(lens[n]))
        np.testing.assert_allclose(got[n, 0], nll, rtol=1e-3, atol=1e-3)


def test_crf_decoding_brute_force():
    rng = np.random.RandomState(14)
    N, T, C = 3, 4, 3
    em = rng.randn(N, T, C).astype(np.float32)
    tr = rng.randn(C + 2, C).astype(np.float32)
    lens = np.array([4, 3, 2])
    got = ops.crf_decoding(t(em), t(tr), length=t(lens)).numpy()
    for n in range(N):
        L = int(lens[n])
        _, best = _brute_crf(em[n], tr, np.zeros(T, np.int64), L)
        np.testing.assert_array_equal(got[n, :L], best)
        np.testing.assert_array_equal(got[n, L:], 0)


def test_crf_grad_flows():
    from op_test import check_grad
    rng = np.random.RandomState(15)
    em = rng.randn(2, 3, 3).astype(np.float32)
    tr = rng.randn(5, 3).astype(np.float32)
    lab = paddle.to_tensor(np.array([[0, 1, 2], [2, 1, 0]], np.int64))
    check_grad(lambda e, w: ops.linear_chain_crf(e, w, lab), [em, tr])


def test_viterbi_decode_square_transition():
    rng = np.random.RandomState(16)
    em = rng.randn(2, 5, 4).astype(np.float32)
    W = rng.randn(4, 4).astype(np.float32)
    lens = np.array([5, 4])
    scores, paths = ops.viterbi_decode(t(em), t(W), t(lens),
                                       include_bos_eos_tag=False)
    # brute force without start/stop
    for n in range(2):
        L = int(lens[n])
        best, bs = None, -np.inf
        for p in itertools.product(range(4), repeat=L):
            s = em[n, 0, p[0]] + sum(em[n, k, p[k]] + W[p[k - 1], p[k]]
                                     for k in range(1, L))
            if s > bs:
                bs, best = s, p
        np.testing.assert_allclose(float(scores.numpy()[n]), bs, rtol=1e-4)
        np.testing.assert_array_equal(paths.numpy()[n, :L], best)


def test_chunk_eval_iob():
    # tags: type*2 + {0:B, 1:I}; 2 chunk types, O = anything outside range
    lab = np.array([[0, 1, 9, 2, 3, 3]])    # chunks: T0[0..1], T1[3..5]
    inf = np.array([[0, 1, 9, 2, 3, 9]])    # chunks: T0[0..1], T1[3..4]
    p, r, f1, ni, nl, nc = ops.chunk_eval(inf, lab, "IOB", 2)
    assert (ni, nl, nc) == (2, 2, 1)
    assert abs(p - 0.5) < 1e-9 and abs(r - 0.5) < 1e-9


def test_chunk_eval_iobes():
    # IOBES: type*4 + {0:B,1:I,2:E,3:S}
    lab = np.array([[3, 0, 1, 2]])          # S chunk [0], B-I-E chunk [1..3]
    inf = np.array([[3, 0, 1, 2]])
    p, r, f1, ni, nl, nc = ops.chunk_eval(inf, lab, "IOBES", 1)
    assert (ni, nl, nc) == (2, 2, 2) and f1 == 1.0


def test_viterbi_decode_bos_eos_convention():
    """Pins the documented layout: row C-2 = BOS->tag, col C-1 = tag->EOS."""
    rng = np.random.RandomState(17)
    C = 4
    em = rng.randn(1, 3, C).astype(np.float32)
    W = rng.randn(C, C).astype(np.float32)
    lens = np.array([3])
    scores, paths = ops.viterbi_decode(t(em), t(W), t(lens),
                                       include_bos_eos_tag=True)
    start, stop = W[C - 2], W[:, C - 1]
    best, bs = None, -np.inf
    for p in itertools.product(range(C), repeat=3):
        s = (start[p[0]] + em[0, 0, p[0]] + stop[p[2]]
             + sum(em[0, k, p[k]] + W[p[k - 1], p[k]] for k in range(1, 3)))
        if s > bs:
            bs, best = s, p
    np.testing.assert_allclose(float(scores.numpy()[0]), bs, rtol=1e-4)
    np.testing.assert_array_equal(paths.numpy()[0], best)
