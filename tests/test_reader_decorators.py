"""paddle.batch + paddle.reader combinators (reference: batch.py,
reader/decorator.py — same semantics, pure python)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import reader as R


def _r10():
    def r():
        yield from range(10)
    return r


def test_batch_semantics():
    out = list(paddle.batch(_r10(), 3)())
    assert out == [[0, 1, 2], [3, 4, 5], [6, 7, 8], [9]]
    out = list(paddle.batch(_r10(), 3, drop_last=True)())
    assert out == [[0, 1, 2], [3, 4, 5], [6, 7, 8]]


def test_combinators():
    assert list(R.firstn(_r10(), 4)()) == [0, 1, 2, 3]
    assert list(R.chain(_r10(), _r10())()) == list(range(10)) * 2
    assert list(R.map_readers(lambda a, b: a + b, _r10(), _r10())()) == \
        [2 * i for i in range(10)]
    assert sorted(R.shuffle(_r10(), 5)()) == list(range(10))
    assert list(R.buffered(_r10(), 2)()) == list(range(10))
    got = list(R.compose(_r10(), R.map_readers(lambda x: x * 10,
                                               _r10()))())
    assert got == [(i, i * 10) for i in range(10)]
    c = R.cache(_r10())
    assert list(c()) == list(range(10)) and list(c()) == list(range(10))
    got = list(R.xmap_readers(lambda x: x + 1, _r10(), 3, 4, order=True)())
    assert got == [i + 1 for i in range(10)]
    got = sorted(R.xmap_readers(lambda x: x + 1, _r10(), 3, 4)())
    assert got == [i + 1 for i in range(10)]


def test_callbacks_and_sysconfig_surface():
    import os
    assert hasattr(paddle.callbacks, "Callback") or \
        hasattr(paddle.callbacks, "EarlyStopping") or \
        len(dir(paddle.callbacks)) > 3
    assert os.path.isdir(paddle.sysconfig.get_include())
    assert os.path.exists(os.path.join(paddle.sysconfig.get_include(),
                                       "paddle_tpu_capi.h"))


def test_compose_alignment_raises():
    from paddle_tpu.reader import ComposeNotAligned

    def r7():
        yield from range(7)
    import pytest
    with pytest.raises(ComposeNotAligned):
        list(R.compose(_r10(), r7)())
    # check_alignment=False truncates at the shortest, quietly
    assert len(list(R.compose(_r10(), r7, check_alignment=False)())) == 7


def test_reader_errors_surface_not_truncate():
    import pytest

    def bad():
        yield 1
        raise IOError("decode failed")
    with pytest.raises(IOError, match="decode failed"):
        list(R.buffered(bad, 4)())
    with pytest.raises(IOError):
        list(R.xmap_readers(lambda x: x, bad, 2, 4)())

    def bad_map(x):
        if x == 5:
            raise ValueError("corrupt item")
        return x
    with pytest.raises(ValueError, match="corrupt"):
        list(R.xmap_readers(bad_map, _r10(), 2, 4, order=True)())


def test_cache_partial_pass_not_committed():
    calls = []

    def flaky():
        calls.append(1)
        yield 0
        yield 1
        if len(calls) == 1:
            raise IOError("transient")
        yield 2
    c = R.cache(flaky)
    import pytest
    with pytest.raises(IOError):
        list(c())
    assert list(c()) == [0, 1, 2]      # no duplicated prefix


def test_top_level_export_parity_vs_reference():
    """Every name the reference's paddle/__init__.py __all__ exports must
    resolve here (backend-specific ones as documented stubs)."""
    import re
    import paddle_tpu as p
    src = open("/root/reference/python/paddle/__init__.py").read()
    names = re.findall(r"^\s+'([A-Za-z_0-9]+)',\s*$", src, re.M)
    missing = sorted(set(n for n in names if not hasattr(p, n)))
    assert not missing, missing


def test_namespace_export_parity_vs_reference():
    """Same check for every public sub-namespace the reference ships."""
    import re
    import importlib
    pairs = [("static", "paddle_tpu.static"), ("jit", "paddle_tpu.jit"),
             ("utils", "paddle_tpu.utils"),
             ("autograd", "paddle_tpu.autograd"),
             ("distributed", "paddle_tpu.distributed"),
             ("distributed/fleet", "paddle_tpu.distributed.fleet"),
             ("metric", "paddle_tpu.metric"),
             ("optimizer", "paddle_tpu.optimizer"),
             ("io", "paddle_tpu.io"), ("text", "paddle_tpu.text"),
             ("amp", "paddle_tpu.amp"),
             ("vision/transforms", "paddle_tpu.vision.transforms"),
             ("vision/datasets", "paddle_tpu.vision.datasets"),
             ("incubate", "paddle_tpu.incubate")]
    bad = {}
    for ref, ourmod in pairs:
        rsrc = open(
            f"/root/reference/python/paddle/{ref}/__init__.py").read()
        names = re.findall(r"from [\w.]+ import (\w+)", rsrc)
        names += re.findall(r"^\s+'(\w+)',?\s*$", rsrc, re.M)
        ours = importlib.import_module(ourmod)
        missing = sorted(set(n for n in names if not n.startswith("_")
                             and not hasattr(ours, n)))
        if missing:
            bad[ref] = missing
    assert not bad, bad


def test_inplace_aliases_keep_gradients():
    """tanh_/scatter_ must stay on the tape (round-5 review: direct
    _data assignment silently dropped the op from backward)."""
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.array([0.5, 2.0], np.float32),
                         stop_gradient=False)
    y = x * 2.0
    paddle.tanh_(y)
    y.sum().backward()
    # d/dx sum(tanh(2x)) = 2 * (1 - tanh^2(2x))
    ref = 2.0 * (1.0 - np.tanh(np.array([1.0, 4.0])) ** 2)
    np.testing.assert_allclose(x.grad.numpy(), ref, rtol=1e-3,
                               atol=1e-6)


def test_add_n_never_aliases():
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.zeros(3, np.float32))
    y = paddle.add_n(x)
    assert y is not x
    paddle.tanh_(y)          # mutating y must not touch x
    np.testing.assert_allclose(x.numpy(), 0.0)
    z = paddle.add_n([x])
    assert z is not x


def test_lookahead_and_model_average():
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as optim
    from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage
    paddle.seed(0)
    net = paddle.nn.Linear(4, 2)
    inner = optim.SGD(learning_rate=0.5, parameters=net.parameters())
    la = LookAhead(inner, alpha=0.5, k=2)
    x = paddle.to_tensor(np.ones((3, 4), np.float32))
    w0 = net.weight.numpy().copy()
    for _ in range(2):
        net(x).sum().backward()
        la.step()
        la.clear_grad()
    g = np.ones_like(w0) * 3.0
    expect = w0 + 0.5 * ((w0 - g) - w0)   # slow <- slow+0.5(fast2-slow)
    np.testing.assert_allclose(net.weight.numpy(), expect, rtol=1e-5)

    ma = ModelAverage(0.5, parameters=net.parameters(),
                      min_average_window=2, max_average_window=4)
    vals = []
    for _ in range(3):
        net.weight._data = net.weight._data + 1.0
        ma.step()
        vals.append(net.weight.numpy().copy())
    cur = net.weight.numpy().copy()
    with ma.apply():
        np.testing.assert_allclose(net.weight.numpy(),
                                   np.mean(vals, axis=0), rtol=1e-5)
    np.testing.assert_allclose(net.weight.numpy(), cur)
