"""Round-5 op tail, part 3: the word-boundary stragglers the tightened
tools/op_coverage.py --check surfaced (asin/atan/tan/erf/imag, assign
family incl. memcpy + rnn_memory_helper aliases, fill_constant, loss and
norm functionals, reductions, reverse, gaussian_random, the nn.rnn
module symbol, hierarchical_sigmoid alias)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
import paddle_tpu.ops as ops
from op_test import check_output


def _rng(s=0):
    return np.random.RandomState(s)


def T(a):
    return paddle.to_tensor(a)


def test_trig_and_special():
    x = (_rng(1).rand(3, 4).astype(np.float32) - 0.5) * 1.8
    check_output(paddle.asin, np.arcsin, [x], rtol=1e-5)
    check_output(paddle.atan, np.arctan, [x], rtol=1e-5)
    check_output(paddle.tan, np.tan, [x], rtol=1e-5)
    import math
    check_output(paddle.erf, np.vectorize(math.erf), [x], rtol=1e-5)
    z = (x + 1j * x[::-1]).astype(np.complex64)
    np.testing.assert_allclose(paddle.imag(T(z)).numpy(), z.imag)


def test_assign_family_and_fill_constant():
    # assign is also the mapping for memcpy and rnn_memory_helper
    x = _rng(2).randn(2, 3).astype(np.float32)
    np.testing.assert_array_equal(paddle.assign(T(x)).numpy(), x)
    got = ops.assign_value([2, 3], "float32",
                           [float(v) for v in x.ravel()])
    np.testing.assert_allclose(got.numpy(), x, rtol=1e-6)
    np.testing.assert_array_equal(
        ops.fill_constant([2, 2], 3.5, "float32").numpy(),
        np.full((2, 2), 3.5, np.float32))


def test_losses_and_norm_functionals():
    r = _rng(3)
    p = r.rand(5, 1).astype(np.float32) * 0.8 + 0.1
    y = (r.rand(5, 1) > 0.5).astype(np.float32)
    ref = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
    np.testing.assert_allclose(
        F.binary_cross_entropy(T(p), T(y)).numpy(), ref, rtol=1e-5)
    x = r.randn(6).astype(np.float32) * 2
    t = r.randn(6).astype(np.float32)
    d = x - t
    # huber_loss_op.cc is elementwise (no reduction attr)
    ref = np.where(np.abs(d) <= 1.0, 0.5 * d * d, np.abs(d) - 0.5)
    np.testing.assert_allclose(
        F.huber_loss(T(x), T(t), delta=1.0).numpy(), ref, rtol=1e-5)
    ref = np.where(np.abs(d) < 1.0, 0.5 * d * d, np.abs(d) - 0.5)
    np.testing.assert_allclose(
        ops.smooth_l1_loss(T(x), T(t), reduction="none").numpy(), ref,
        rtol=1e-5)
    # layer_norm / group_norm vs torch oracle
    import torch
    h = r.randn(4, 6).astype(np.float32)
    np.testing.assert_allclose(
        F.layer_norm(T(h), 6).numpy(),
        torch.nn.functional.layer_norm(torch.from_numpy(h), (6,)).numpy(),
        rtol=1e-4, atol=1e-5)
    img = r.randn(2, 4, 3, 3).astype(np.float32)
    np.testing.assert_allclose(
        F.group_norm(T(img), 2).numpy(),
        torch.nn.functional.group_norm(torch.from_numpy(img), 2).numpy(),
        rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        ops.p_norm(T(h), p=3, axis=1).numpy(),
        (np.abs(h) ** 3).sum(1) ** (1 / 3), rtol=1e-5)


def test_reductions_reverse_random():
    r = _rng(4)
    x = r.rand(3, 4).astype(np.float32) + 0.5
    np.testing.assert_allclose(ops.reduce_sum(T(x), axis=1).numpy(),
                               x.sum(1), rtol=1e-5)
    np.testing.assert_allclose(ops.reduce_prod(T(x), axis=0).numpy(),
                               x.prod(0), rtol=1e-5)
    np.testing.assert_array_equal(ops.reverse(T(x), axis=[1]).numpy(),
                                  x[:, ::-1])
    paddle.seed(5)
    g = paddle.normal(mean=2.0, std=0.5, shape=[20000]).numpy()
    assert abs(g.mean() - 2.0) < 0.02 and abs(g.std() - 0.5) < 0.02


def test_rnn_module_and_hierarchical_sigmoid_alias():
    from paddle_tpu.nn import rnn as rnn_module      # nn:rnn mapping
    assert hasattr(rnn_module, "GRUCell")
    r = _rng(6)
    x = r.randn(4, 8).astype(np.float32)
    lab = r.randint(0, 6, (4,)).astype(np.int64)
    w = r.randn(5, 8).astype(np.float32)
    out = F.hierarchical_sigmoid(T(x), T(lab), 6, T(w))
    np.testing.assert_allclose(
        out.numpy(), F.hsigmoid_loss(T(x), T(lab), 6, T(w)).numpy())
