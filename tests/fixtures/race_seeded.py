"""Seeded lock-discipline bugs — PTA006 acceptance fixture.

Never imported by the package; tests/test_concurrency_lint.py runs the
analyzer on this file and asserts both PTA006 finding classes fire:

- ``bump_unguarded``: a counter the class guards with ``self._lock``
  (see ``incr``) written with no lock held (unguarded-access);
- ``pop_check_then_act``: the emptiness test and the ``pop`` each hold
  the lock, but separately — another thread can drain the list between
  them (check-then-act).
"""
import threading


class SharedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.items = []

    def incr(self):
        with self._lock:
            self.count += 1
            self.items.append(self.count)

    def bump_unguarded(self):
        self.count += 1  # seeded: guarded attr, no lock

    def pop_check_then_act(self):
        if self.items:  # seeded: test outside the lock the pop takes
            with self._lock:
                return self.items.pop()
        return None


def start():
    c = SharedCounter()
    writer = threading.Thread(target=c.bump_unguarded)
    popper = threading.Thread(target=c.pop_check_then_act)
    writer.start()
    popper.start()
    return c
