"""Seeded signal-handler hazards — PTA007 acceptance fixture.

Never imported by the package; tests/test_concurrency_lint.py runs the
analyzer on this file and asserts every PTA007 finding class fires:

- logging inside a handler (error: the logging module's internal locks
  deadlock if the signal lands mid-log);
- lock acquisition inside a handler (error: self-deadlock against the
  interrupted thread);
- a blocking call inside a handler (warning);
- a ``raise`` escaping the handler (warning).
"""
import logging
import signal
import threading
import time

log = logging.getLogger(__name__)
_STATE_LOCK = threading.Lock()


def _on_term(signum, frame):
    log.warning("terminating on signal %s", signum)  # seeded: logging
    with _STATE_LOCK:  # seeded: lock acquisition
        pass
    time.sleep(0.1)  # seeded: blocking call


def _on_int(signum, frame):
    raise KeyboardInterrupt  # seeded: raise escaping the handler


def install():
    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_int)
