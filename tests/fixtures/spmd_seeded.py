"""Seeded true positives for PTA011 (SPMD divergence lint) and PTA012
(collective-schedule audit). Every function here is a deliberate bug —
tests/test_spmd_lint.py asserts the analyzer catches each one and that
clean_* functions stay clean. Never import this module from real code.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec  # noqa: F401 - axis decls


def rank_gated_psum(grads):
    # BUG: rank 0 issues a psum its peers never reach -> deadlock
    if jax.process_index() == 0:
        grads = lax.psum(grads, "dp")
    return grads


def env_rank_gated_allreduce(x):
    # BUG: env-derived rank gates a collective wrapper
    trainer = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if trainer == 0:
        from paddle_tpu.distributed.collective import all_reduce
        x = all_reduce(x)
    return x


def swallowed_collective(x):
    # BUG: one rank's psum failure is swallowed while peers still wait
    try:
        x = lax.psum(x, "dp")
    except Exception:
        pass
    return x


def make_mesh_with_axes():
    devices = jax.devices()
    return Mesh(jax.numpy.array(devices), ("dp", "sp"))


def axis_typo_psum(x):
    # BUG: axis "pd" is declared nowhere (mesh above declares dp/sp)
    return lax.psum(x, "pd")


def host_len_loop_gather(chunks):
    # BUG: trip count derives from this host's rank -> ranks run
    # different numbers of collective rounds
    steps = jax.process_index() + 2
    out = []
    for _ in range(steps):
        out.append(lax.all_gather(chunks, "dp"))
    return out


def clean_uniform_psum(x):
    # OK: every rank runs the same schedule; divergence is in data only
    rank = lax.axis_index("dp")
    masked = jnp.where(rank == 0, x, jnp.zeros_like(x))
    return lax.psum(masked, "dp")


def clean_rank_gated_logging(loss):
    # OK: rank gate guards host-side I/O, not a collective
    if jax.process_index() == 0:
        print("loss:", loss)
    return loss


def make_ring_mesh():
    import numpy as np
    devs = np.array(jax.devices()[:4]).reshape(4)
    return Mesh(devs, ("r",))


def broken_ring_body(x):
    # BUG (PTA012): on a 4-wide axis this perm never involves rank 3 as
    # a source and never delivers to rank 0's slot consistently — the
    # ring is open and rank 3 blocks forever
    return lax.ppermute(x, "r", perm=[(0, 1), (1, 2), (2, 0)])
