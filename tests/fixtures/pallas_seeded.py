"""Seeded true-positives for PTA013 (pallas-kernel-safety).

Never import this module from real code: it exists so
tests/test_pallas_lint.py can run the analyzer against a file with KNOWN
kernel-safety violations and assert each is (a) detected, (b) killable
by `# noqa: PTA013 -- reason`, and (c) killable by baseline. Mirrors the
tests/fixtures/spmd_seeded.py discipline for PTA011.

Four seeded classes (one per PTA013 finding class), then clean_*
controls that must stay finding-free.
"""


def seeded_unguarded_grid(q, k, v, block_q):
    """(a) grid floor-divides by a dynamic block with no divisibility
    guard and no sanitize-helper provenance: a non-dividing block_q
    silently drops the tail rows."""
    import jax.experimental.pallas as pl

    def kernel(q_ref, k_ref, v_ref, o_ref):
        o_ref[...] = q_ref[...]

    seq = q.shape[0]
    return pl.pallas_call(
        kernel,
        grid=(seq // block_q,),  # PTA013(a): unguarded dynamic divisor
        out_shape=q,
        interpret=True,
    )(q, k, v)


def seeded_vmem_bust(x):
    """(b) constant BlockSpec shapes whose combined f32 footprint
    (blockspec_vmem_bytes) busts VMEM_BUDGET: 2 * (1, 8192, 512) blocks
    = 32 MiB against the ~12.8 MiB budget."""
    import jax.experimental.pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    return pl.pallas_call(
        kernel,
        grid=(4,),
        in_specs=[pl.BlockSpec((1, 8192, 512), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((1, 8192, 512), lambda i: (i, 0, 0)),
        out_shape=x,
        interpret=True,
    )(x)


def seeded_bf16_acc_kernel(q_ref, k_ref, o_ref):
    """(c) reduction accumulator declared below f32: online-softmax
    statistics accumulated in bf16 lose the exactness contract."""
    import jax.numpy as jnp

    acc = jnp.zeros((128, 64), jnp.bfloat16)  # PTA013(c): bf16 accumulator
    o_ref[...] = acc + q_ref[...] @ k_ref[...]


def seeded_no_interpret(x):
    """(d) pallas_call with no interpret= lane: unreachable off-TPU, so
    CPU tier-1 can never cover its math."""
    import jax.experimental.pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2

    return pl.pallas_call(  # PTA013(d): no interpret kwarg
        kernel,
        out_shape=x,
    )(x)


# -- clean controls: the sanctioned idioms, must stay finding-free -----------


def clean_guarded_grid(q, block_q):
    """Explicit divisibility guard (the _fa_fwd_with_lse idiom): the mod
    check + raise makes the floor-division exact by construction."""
    import jax.experimental.pallas as pl

    def kernel(q_ref, o_ref):
        o_ref[...] = q_ref[...]

    seq = q.shape[0]
    if seq % block_q:
        raise ValueError("block_q must divide the padded sequence")
    return pl.pallas_call(
        kernel,
        grid=(seq // block_q,),
        out_shape=q,
        interpret=True,
    )(q)


def clean_sanitized_grid(q, block_q, _sanitize_block):
    """Sanitize-helper provenance (the paged_attention _sanitize_block_h
    idiom): the helper clamps the block to an exact divisor."""
    import jax.experimental.pallas as pl

    def kernel(q_ref, o_ref):
        o_ref[...] = q_ref[...]

    seq = q.shape[0]
    block_q = _sanitize_block(block_q, seq)
    return pl.pallas_call(
        kernel,
        grid=(seq // block_q,),
        out_shape=q,
        interpret=True,
    )(q)


def clean_f32_acc_kernel(q_ref, k_ref, o_ref):
    """f32 accumulator plus an int32 mask: both legal — only sub-f32
    FLOAT accumulators are findings."""
    import jax.numpy as jnp

    acc = jnp.zeros((128, 64), jnp.float32)
    mask = jnp.zeros((128, 1), jnp.int32)
    o_ref[...] = acc + q_ref[...] @ k_ref[...] + mask
