"""TP layers, ZeRO sharding, DistributedStrategy, recompute, gradient merge
(reference analogs: unittests/test_parallel_dygraph_mp_layers.py,
test_fleet_sharding_meta_optimizer.py, test_fleet_distributed_strategy.py,
test_fleet_recompute_meta_optimizer.py)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import stable_uid
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import (DistributedStrategy,
                                          ColumnParallelLinear,
                                          RowParallelLinear,
                                          VocabParallelEmbedding)


@pytest.fixture
def mp_mesh():
    dist.set_mesh(dist.build_mesh({"dp": 2, "mp": 4}))
    yield dist.get_mesh()
    dist.set_mesh(None)


class TestTPLayers:
    def test_column_parallel_matches_dense(self, mp_mesh):
        paddle.seed(0)
        col = ColumnParallelLinear(16, 32, gather_output=True)
        dense = nn.Linear(16, 32)
        dense.weight.set_value(col.weight.numpy())
        dense.bias.set_value(col.bias.numpy())
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 16).astype(np.float32))
        np.testing.assert_allclose(col(x).numpy(), dense(x).numpy(),
                                   atol=1e-5)
        # weight is physically sharded over mp
        assert "mp" in str(col.weight._data.sharding.spec)

    def test_row_parallel_matches_dense(self, mp_mesh):
        paddle.seed(0)
        row = RowParallelLinear(16, 8, input_is_parallel=False)
        dense = nn.Linear(16, 8)
        dense.weight.set_value(row.weight.numpy())
        dense.bias.set_value(row.bias.numpy())
        x = paddle.to_tensor(np.random.RandomState(1)
                             .randn(4, 16).astype(np.float32))
        np.testing.assert_allclose(row(x).numpy(), dense(x).numpy(),
                                   atol=1e-5)

    def test_column_row_composition_grads(self, mp_mesh):
        """Megatron MLP block: col(gather=False) -> row(input_is_parallel)."""
        paddle.seed(0)
        col = ColumnParallelLinear(8, 16, gather_output=False)
        row = RowParallelLinear(16, 8, input_is_parallel=True)
        x = paddle.to_tensor(np.random.RandomState(2)
                             .randn(4, 8).astype(np.float32))
        out = row(paddle.nn.functional.relu(col(x)))
        loss = paddle.mean(out ** 2)
        loss.backward()
        assert col.weight.grad is not None and row.weight.grad is not None
        # numerics equal the dense composition
        w1, b1 = col.weight.numpy(), col.bias.numpy()
        w2, b2 = row.weight.numpy(), row.bias.numpy()
        h = np.maximum(x.numpy() @ w1 + b1, 0)
        expected = h @ w2 + b2
        np.testing.assert_allclose(out.numpy(), expected, atol=1e-5)

    def test_vocab_parallel_embedding(self, mp_mesh):
        paddle.seed(0)
        emb = VocabParallelEmbedding(32, 8)
        ids = paddle.to_tensor(np.array([[1, 5, 31]], np.int32))
        out = emb(ids)
        np.testing.assert_allclose(out.numpy(),
                                   emb.weight.numpy()[[1, 5, 31]][None],
                                   atol=1e-6)
        assert "mp" in str(emb.weight._data.sharding.spec)

    def test_tp_under_jit_train_step(self, mp_mesh):
        """The compiled fused step must accept mp-sharded params."""
        paddle.seed(0)

        class Block(nn.Layer):
            def __init__(self):
                super().__init__()
                self.col = ColumnParallelLinear(8, 16, gather_output=False)
                self.row = RowParallelLinear(16, 8, input_is_parallel=True)

            def forward(self, x):
                return self.row(paddle.nn.functional.relu(self.col(x)))

        net = Block()
        opt = optim.AdamW(learning_rate=1e-3, parameters=net.parameters(),
                          weight_decay=0.0)
        m = paddle.Model(net)
        m.prepare(opt, nn.MSELoss())
        X = np.random.RandomState(0).randn(8, 8).astype(np.float32)
        l1, _ = m.train_batch([X], [X])
        l2, _ = m.train_batch([X], [X])
        assert np.isfinite(l1) and l2 < l1


class TestZeroSharding:
    def test_sharded_adam_matches_replicated(self):
        dist.set_mesh(dist.build_mesh({"dp": 8}))
        try:
            def run(shard):
                paddle.seed(3)
                net = nn.Linear(16, 16)
                opt = optim.Adam(learning_rate=0.01,
                                 parameters=net.parameters())
                if shard:
                    dist.sharding.shard_optimizer_states(opt)
                X = np.random.RandomState(1).randn(8, 16).astype(np.float32)
                for _ in range(3):
                    loss = paddle.mean((net(paddle.to_tensor(X))) ** 2)
                    loss.backward()
                    opt.step()
                    opt.clear_grad()
                return net.weight.numpy(), opt

            w_ref, _ = run(False)
            w_sh, opt = run(True)
            np.testing.assert_allclose(w_sh, w_ref, atol=1e-6)
            st = opt._state[stable_uid(opt._parameter_list[0])]
            assert "dp" in str(st["moment1"].sharding.spec)
        finally:
            dist.set_mesh(None)

    def test_group_sharded_parallel_levels(self):
        dist.set_mesh(dist.build_mesh({"dp": 8}))
        try:
            net = nn.Linear(16, 4)
            opt = optim.Adam(learning_rate=0.01, parameters=net.parameters())
            net, opt, _ = dist.group_sharded_parallel(net, opt, level="p_g_os")
            assert "dp" in str(net.weight._data.sharding.spec)
            loss = paddle.mean(net(paddle.to_tensor(
                np.ones((4, 16), np.float32))) ** 2)
            loss.backward()
            opt.step()
            with pytest.raises(ValueError):
                dist.group_sharded_parallel(net, opt, level="bogus")
        finally:
            dist.set_mesh(None)


class TestDistributedStrategy:
    def test_json_roundtrip(self, tmp_path):
        st = DistributedStrategy()
        st.sharding = True
        st.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
        st.gradient_merge = True
        st.gradient_merge_configs = {"k_steps": 4}
        path = str(tmp_path / "strategy.json")
        st.save_to_prototxt(path)
        st2 = DistributedStrategy()
        st2.load_from_prototxt(path)
        assert st == st2
        assert st2.hybrid_configs["mp_degree"] == 4
        assert st2.gradient_merge_configs["k_steps"] == 4
        assert st2.gradient_merge_configs["avg"] is True  # merged defaults

    def test_unknown_field_raises(self):
        st = DistributedStrategy()
        with pytest.raises(AttributeError):
            st.bogus_field = 1

    def test_mesh_axes(self):
        st = DistributedStrategy()
        st.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
        assert st.mesh_axes() == {"dp": 2, "pp": 2, "mp": 2}


class TestFleetFacade:
    def test_init_and_distributed_model_dp(self):
        st = DistributedStrategy()
        fleet.init(is_collective=True, strategy=st)
        net = fleet.distributed_model(nn.Linear(4, 2))
        assert isinstance(net, paddle.DataParallel)
        dist.set_mesh(None)

    def test_distributed_optimizer_sharding_and_merge(self):
        st = DistributedStrategy()
        st.sharding = True
        st.gradient_merge = True
        st.gradient_merge_configs = {"k_steps": 2, "avg": True}
        fleet.init(is_collective=True, strategy=st)
        p = paddle.Parameter(np.zeros((8,), np.float32))
        opt = fleet.distributed_optimizer(
            optim.SGD(learning_rate=1.0, parameters=[p]), st)
        # two accumulation steps then one update of the average
        p._grad = jnp.ones(8)
        opt.step()
        np.testing.assert_allclose(p.numpy(), 0.0)  # not applied yet
        p._grad = jnp.ones(8) * 3
        opt.step()
        np.testing.assert_allclose(p.numpy(), -2.0)  # (1+3)/2 applied
        dist.set_mesh(None)


class TestRecompute:
    def test_recompute_numerics_identical(self):
        from paddle_tpu.distributed.fleet.utils import recompute

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(8, 32)
                self.b = nn.Linear(32, 8)
                self.use_rc = False

            def forward(self, x):
                if self.use_rc:
                    h = recompute(lambda v: paddle.nn.functional.relu(
                        self.a(v)), x)
                else:
                    h = paddle.nn.functional.relu(self.a(x))
                return self.b(h)

        paddle.seed(5)
        net = Net()
        from paddle_tpu.jit import to_static
        X = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 8).astype(np.float32))
        plain = net(X).numpy()
        net.use_rc = True
        st = to_static(net)
        np.testing.assert_allclose(st(X).numpy(), plain, atol=1e-5)

    def test_recompute_grads_match(self):
        from paddle_tpu.distributed.fleet.utils import recompute

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(4, 16)
                self.b = nn.Linear(16, 1)
                self.use_rc = False

            def forward(self, x):
                if self.use_rc:
                    h = recompute(lambda v: paddle.tanh(self.a(v)), x)
                else:
                    h = paddle.tanh(self.a(x))
                return self.b(h)

        def grads(use_rc):
            paddle.seed(7)
            net = Net()
            net.use_rc = use_rc
            from paddle_tpu.jit import to_static
            st = to_static(net) if use_rc else net
            X = paddle.to_tensor(np.random.RandomState(1)
                                 .randn(8, 4).astype(np.float32))
            loss = paddle.mean(st(X) ** 2)
            loss.backward()
            return net.a.weight.grad.numpy()

        np.testing.assert_allclose(grads(True), grads(False), atol=1e-5)


class TestDistributedSplit:
    """reference: distributed/collective.py:1154 split — one-call MP layer
    builder (GSPMD style: call under the mesh, not inside shard_map)."""

    def test_column_split_output_shape_and_sharding(self, mp_mesh):
        paddle.seed(0)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 16).astype(np.float32))
        out = dist.split(x, (16, 32), operation="linear", axis=1,
                         gather_out=True)
        assert out.shape == [4, 32]

    def test_row_split(self, mp_mesh):
        paddle.seed(0)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 16).astype(np.float32))
        out = dist.split(x, (16, 8), operation="linear", axis=0)
        assert out.shape == [4, 8]

    def test_embedding_split(self, mp_mesh):
        paddle.seed(0)
        ids = paddle.to_tensor(np.array([[1, 5, 31]], np.int32))
        out = dist.split(ids, (32, 16), operation="embedding")
        assert out.shape == [1, 3, 16]

    def test_bad_partitions_raises(self, mp_mesh):
        with pytest.raises(ValueError, match="num_partitions"):
            dist.split(paddle.to_tensor(np.zeros((2, 16), np.float32)),
                       (16, 32), operation="linear", axis=1,
                       num_partitions=2)
