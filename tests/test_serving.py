"""paddle_tpu.serving: dynamic batching, shape buckets, executable cache,
deadlines/backpressure, graceful drain, and the end-to-end acceptance run
(64 concurrent mixed-size requests, bitwise vs the serial Predictor)."""
import signal
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.monitor import StatRegistry
from paddle_tpu.distributed.elastic import PreemptionGuard
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.serving import (
    BatchQueue, BucketSpec, DynamicBatcher, Engine, EngineConfig,
    EngineDraining, ExecutableCache, InferenceRequest, QueueFull,
    RequestTooLarge, pow2_buckets)
from paddle_tpu.serving.batcher import Batch
from paddle_tpu.serving.buckets import pad_rows, pad_seq, unpad_rows
from paddle_tpu.static import InputSpec
from paddle_tpu.utils.resilience import Deadline, DeadlineExceeded


def _identity_model(*arrays):
    return [np.asarray(a) * 2.0 for a in arrays]


def _mk_engine(model=_identity_model, **cfg):
    cfg.setdefault("max_batch", 8)
    cfg.setdefault("max_batch_delay", 0.01)
    return Engine(model, EngineConfig(**cfg), registry=StatRegistry())


# ---------------------------------------------------------------------------
class TestBuckets:
    def test_pow2(self):
        assert pow2_buckets(16) == (1, 2, 4, 8, 16)
        assert pow2_buckets(12) == (1, 2, 4, 8, 12)

    def test_bucket_for(self):
        spec = BucketSpec(max_batch=16)
        assert spec.batch_bucket_for(1) == 1
        assert spec.batch_bucket_for(5) == 8
        assert spec.batch_bucket_for(16) == 16
        assert spec.batch_bucket_for(17) is None

    def test_seq_buckets(self):
        spec = BucketSpec(max_batch=8, seq_buckets=[16, 64])
        assert spec.seq_bucket_for(5) == 16
        assert spec.seq_bucket_for(64) == 64
        assert spec.seq_bucket_for(100) == 100  # above the largest: as-is
        assert BucketSpec(max_batch=8).seq_bucket_for(7) == 7

    def test_pad_unpad_roundtrip(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        padded = pad_rows([x], 8)[0]
        assert padded.shape == (8, 4)
        assert np.array_equal(padded[:3], x)
        assert not padded[3:].any()
        assert np.array_equal(unpad_rows([padded], 3)[0], x)

    def test_pad_seq(self):
        x = np.ones((2, 5), np.float32)
        y = pad_seq([x], 16)[0]
        assert y.shape == (2, 16)
        assert y[:, :5].all() and not y[:, 5:].any()
        # rank-1 arrays (e.g. lengths) are left alone
        lens = np.array([5, 5])
        assert pad_seq([lens], 16)[0] is lens


# ---------------------------------------------------------------------------
class TestExecutableCache:
    def test_hit_miss_counters(self):
        c = ExecutableCache(capacity=4)
        calls = []
        f = c.get_or_compile("k1", lambda: calls.append(1) or "exe1")
        assert f == "exe1" and c.misses == 1 and c.hits == 0
        f = c.get_or_compile("k1", lambda: calls.append(1) or "exe1b")
        assert f == "exe1" and c.hits == 1 and len(calls) == 1

    def test_lru_eviction(self):
        c = ExecutableCache(capacity=2)
        c.get_or_compile("a", lambda: "A")
        c.get_or_compile("b", lambda: "B")
        c.get_or_compile("a", lambda: "A")   # refresh a
        c.get_or_compile("c", lambda: "C")   # evicts b (LRU)
        assert c.evictions == 1
        assert c.contains("a") and c.contains("c") and not c.contains("b")

    def test_stats_shape(self):
        s = ExecutableCache().stats()
        assert set(s) == {"size", "capacity", "hits", "misses", "evictions"}


# ---------------------------------------------------------------------------
class TestMonitorHistogram:
    def test_observe_quantile(self):
        reg = StatRegistry()
        for v in range(1, 101):
            reg.observe("lat", float(v))
        assert reg.quantile("lat", 0.5) == pytest.approx(50.5)
        assert reg.quantile("lat", 0.99) == pytest.approx(99.01)
        s = reg.histogram("lat")
        assert s["count"] == 100 and s["min"] == 1.0 and s["max"] == 100.0

    def test_bounded_reservoir(self):
        reg = StatRegistry()
        for v in range(10):
            reg.observe("x", float(v), max_samples=4)
        s = reg.histogram("x")
        assert s["count"] == 10          # all-time count
        assert s["min"] == 0.0
        assert reg.quantile("x", 0.0) == 6.0  # window kept newest 4

    def test_missing_and_reset(self):
        reg = StatRegistry()
        assert reg.quantile("nope", 0.5, default=-1.0) == -1.0
        reg.observe("y", 3.0)
        reg.reset("y")
        assert reg.histogram("y")["count"] == 0

    def test_module_level_helpers(self):
        from paddle_tpu.core.monitor import stat_observe, stat_quantile
        stat_observe("test.serving.hist", 7.0)
        assert stat_quantile("test.serving.hist", 0.5) == 7.0


# ---------------------------------------------------------------------------
class TestBatchQueue:
    def test_fifo_and_fits(self):
        q = BatchQueue(max_size=4)
        a = InferenceRequest([np.zeros((2, 3))])
        b = InferenceRequest([np.zeros((5, 3))])
        q.put(a)
        q.put(b)
        got = q.take(timeout=0.1, fits=lambda r: r.nrows <= 2)
        assert got is a
        # head b does not fit: stays queued, take returns None
        assert q.take(timeout=0.05, fits=lambda r: r.nrows <= 2) is None
        assert len(q) == 1

    def test_admission_reject_when_full(self):
        q = BatchQueue(max_size=1)
        q.put(InferenceRequest([np.zeros((1, 1))]))
        with pytest.raises(QueueFull):
            q.put(InferenceRequest([np.zeros((1, 1))]), block=False)
        with pytest.raises(QueueFull):
            q.put(InferenceRequest([np.zeros((1, 1))]), timeout=0.05)

    def test_close_unblocks_putter(self):
        q = BatchQueue(max_size=1)
        q.put(InferenceRequest([np.zeros((1, 1))]))
        errs = []

        def blocked_put():
            try:
                q.put(InferenceRequest([np.zeros((1, 1))]), timeout=5.0)
            except EngineDraining as e:
                errs.append(e)

        t = threading.Thread(target=blocked_put)
        t.start()
        time.sleep(0.05)
        q.close()
        t.join(2.0)
        assert len(errs) == 1

    def test_deadline_eviction_at_head(self):
        q = BatchQueue(max_size=4)
        dead = InferenceRequest([np.zeros((1, 1))], deadline=Deadline(0))
        live = InferenceRequest([np.zeros((1, 1))])
        q.put(dead)
        q.put(live)
        got = q.take(timeout=0.1)
        assert got is live
        assert q.evicted_expired == 1
        with pytest.raises(DeadlineExceeded):
            dead.future.result(0)


# ---------------------------------------------------------------------------
class TestDynamicBatcher:
    def test_empty_queue_timeout_flush(self):
        q = BatchQueue()
        b = DynamicBatcher(q, BucketSpec(max_batch=8), max_batch_delay=0.005)
        t0 = time.monotonic()
        assert b.next_batch(timeout=0.05) is None
        assert time.monotonic() - t0 < 1.0

    def test_coalesces_and_buckets(self):
        q = BatchQueue()
        for n in (2, 3, 1):
            q.put(InferenceRequest([np.zeros((n, 4))]))
        b = DynamicBatcher(q, BucketSpec(max_batch=8), max_batch_delay=0.05)
        batch = b.next_batch(timeout=0.1)
        assert len(batch.requests) == 3 and batch.rows == 6
        assert batch.bucket_rows == 8 and not batch.oversize
        assert batch.fill_ratio == pytest.approx(6 / 8)

    def test_stops_at_max_bucket(self):
        q = BatchQueue()
        for n in (6, 6):
            q.put(InferenceRequest([np.zeros((n, 4))]))
        b = DynamicBatcher(q, BucketSpec(max_batch=8), max_batch_delay=0.05)
        batch = b.next_batch(timeout=0.1)
        assert [r.nrows for r in batch.requests] == [6]
        assert batch.bucket_rows == 8
        assert len(q) == 1  # second request left for the next batch

    def test_oversize_flag(self):
        q = BatchQueue()
        q.put(InferenceRequest([np.zeros((20, 4))]))
        b = DynamicBatcher(q, BucketSpec(max_batch=8), max_batch_delay=0.0)
        batch = b.next_batch(timeout=0.1)
        assert batch.oversize and batch.bucket_rows is None


# ---------------------------------------------------------------------------
class TestEngine:
    def test_submit_and_result(self):
        eng = _mk_engine()
        x = np.arange(6, dtype=np.float32).reshape(3, 2)
        out, = eng.submit([x]).result(10)
        assert np.array_equal(out, x * 2.0)
        eng.drain()

    def test_submit_many(self):
        eng = _mk_engine()
        xs = [[np.full((n, 2), float(n), np.float32)] for n in (1, 2, 3)]
        futs = eng.submit_many(xs)
        for n, f in zip((1, 2, 3), futs):
            out, = f.result(10)
            assert out.shape == (n, 2) and np.all(out == 2.0 * n)
        eng.drain()

    def test_oversize_split_matches(self):
        eng = _mk_engine(max_batch=4, oversize_policy="split")
        x = np.random.RandomState(0).randn(11, 3).astype(np.float32)
        out, = eng.submit([x]).result(10)
        assert np.array_equal(out, x * 2.0)
        assert eng.registry.get("serving.oversize_splits") == 1
        eng.drain()

    def test_oversize_reject(self):
        eng = _mk_engine(max_batch=4, oversize_policy="reject")
        with pytest.raises(RequestTooLarge):
            eng.submit([np.zeros((5, 3), np.float32)])
        eng.drain()

    def test_deadline_expired_request_evicted(self):
        release = threading.Event()

        def slow_model(x):
            release.wait(5.0)
            return [np.asarray(x)]

        eng = _mk_engine(model=slow_model, max_batch=1, max_batch_delay=0.0)
        f_block = eng.submit([np.zeros((1, 2), np.float32)])
        time.sleep(0.05)  # worker is now stuck inside slow_model
        f_dead = eng.submit([np.zeros((1, 2), np.float32)], deadline=0.01)
        time.sleep(0.1)   # deadline passes while queued
        release.set()
        with pytest.raises(DeadlineExceeded):
            f_dead.result(10)
        assert f_block.result(10)[0].shape == (1, 2)
        eng.drain()

    def test_drain_with_inflight_returns_all_futures(self):
        def slow_model(x):
            time.sleep(0.03)
            return [np.asarray(x) * 2.0]

        eng = _mk_engine(model=slow_model, max_batch=1, max_batch_delay=0.0)
        futs = [eng.submit([np.full((1, 2), i, np.float32)])
                for i in range(6)]
        inflight = eng.drain(timeout=30)
        assert len(inflight) >= 1          # drain began with work in flight
        assert all(f.done() for f in futs)
        for i, f in enumerate(futs):
            assert np.all(f.result(0)[0] == 2.0 * i)
        with pytest.raises(EngineDraining):
            eng.submit([np.zeros((1, 2), np.float32)])

    def test_preemption_guard_triggers_drain(self):
        eng = _mk_engine()
        guard = PreemptionGuard(install=False)
        eng.arm_preemption(guard)
        f = eng.submit([np.ones((2, 2), np.float32)])
        f.result(10)
        guard.preempt()
        assert eng._stopped.wait(10)
        assert eng.draining
        assert eng.registry.get("serving.preemption_drains") == 1

    def test_queue_full_backpressure(self):
        release = threading.Event()

        def slow_model(x):
            release.wait(5.0)
            return [np.asarray(x)]

        eng = _mk_engine(model=slow_model, max_batch=1, max_batch_delay=0.0,
                         max_queue=1, admission_block=False)
        eng.submit([np.zeros((1, 1), np.float32)])
        time.sleep(0.05)  # worker busy; next two fill + overflow the queue
        eng.submit([np.zeros((1, 1), np.float32)])
        with pytest.raises(QueueFull):
            eng.submit([np.zeros((1, 1), np.float32)])
        assert eng.registry.get("serving.rejected_queue_full") == 1
        release.set()
        eng.drain()


# ---------------------------------------------------------------------------
class TestSignalChaining:
    """Regression: serving drain + elastic PreemptionGuard must chain, not
    clobber, each other's signal handlers (either install order)."""

    SIG = signal.SIGUSR1

    def test_guard_then_engine(self):
        original = signal.getsignal(self.SIG)
        eng = _mk_engine()
        guard = PreemptionGuard(signals=(self.SIG,))
        chain = eng.install_drain_signal_handler(signals=(self.SIG,))
        try:
            signal.raise_signal(self.SIG)
            assert guard.preempted           # earlier handler still fired
            assert eng.draining              # new handler fired too
        finally:
            chain.uninstall()
            guard.uninstall()
            eng.drain()
        assert signal.getsignal(self.SIG) == original

    def test_engine_then_guard(self):
        original = signal.getsignal(self.SIG)
        eng = _mk_engine()
        chain = eng.install_drain_signal_handler(signals=(self.SIG,))
        guard = PreemptionGuard(signals=(self.SIG,))
        try:
            signal.raise_signal(self.SIG)
            assert guard.preempted
            assert eng.draining
        finally:
            guard.uninstall()
            chain.uninstall()
            eng.drain()
        assert signal.getsignal(self.SIG) == original


# ---------------------------------------------------------------------------
class TestServingE2E:
    """Acceptance: >= 64 concurrent mixed-size requests through Engine are
    bitwise-identical to serial Predictor.run, with coalescing, zero
    executable-cache misses after warmup, and live /statsz percentiles."""

    def _export(self, tmp_path):
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(6, 16)
                self.fc2 = nn.Linear(16, 5)

            def forward(self, x):
                return nn.functional.softmax(
                    self.fc2(nn.functional.relu(self.fc1(x))), axis=-1)

        net = Net()
        prefix = str(tmp_path / "served")
        # None batch dim -> shape-polymorphic StableHLO artifact
        paddle.jit.save(net, prefix,
                        input_spec=[InputSpec([None, 6], "float32", "x")])
        return prefix

    @pytest.mark.timeout_s(240)
    def test_e2e_64_concurrent_requests(self, tmp_path):
        from concurrent.futures import ThreadPoolExecutor
        prefix = self._export(tmp_path)
        pred = create_predictor(Config(prefix))

        rng = np.random.RandomState(42)
        sizes = [1, 2, 3, 4, 5, 6, 7, 8] * 8          # 64 mixed-size
        payloads = [rng.randn(n, 6).astype(np.float32) for n in sizes]
        serial = [pred.run([x])[0] for x in payloads]  # serial reference

        reg = StatRegistry()
        eng = Engine(pred, EngineConfig(max_batch=16, max_batch_delay=0.02,
                                        max_queue=128), registry=reg)
        # warmup: compile every bucket shape once
        for b in (1, 2, 4, 8, 16):
            eng.submit([np.zeros((b, 6), np.float32)]).result(60)
        misses_after_warmup = eng.cache.stats()["misses"]

        with ThreadPoolExecutor(16) as ex:
            futs = list(ex.map(lambda x: eng.submit([x]), payloads))
        outs = [f.result(60) for f in futs]

        # bitwise-identical to the serial Predictor
        for (out,), ref in zip(outs, serial):
            assert np.array_equal(out, ref)
        # at least one batch actually coalesced >= 2 requests
        assert reg.get("serving.coalesced_batches") >= 1
        # zero cache misses after warmup: every batch hit a bucketed shape
        assert eng.cache.stats()["misses"] == misses_after_warmup
        # latency + fill observability
        assert reg.quantile("serving.latency_ms", 0.5) > 0
        assert reg.quantile("serving.batch_fill", 0.5) > 0

        # /statsz over HTTP reports the same non-zero percentiles
        import json
        import urllib.request
        from paddle_tpu.serving.http import make_server
        srv = make_server(eng, port=0)
        port = srv.server_address[1]
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/statsz") as r:
                stats = json.loads(r.read())
        finally:
            srv.shutdown()
            srv.server_close()
        lat = stats["histograms"]["serving.latency_ms"]
        fill = stats["histograms"]["serving.batch_fill"]
        assert lat["p50"] > 0 and lat["p99"] >= lat["p50"]
        assert 0 < fill["p50"] <= 1.0
        assert stats["executable_cache"]["misses"] == misses_after_warmup

        inflight = eng.drain(timeout=30)
        assert all(f.done() for f in inflight)

    def test_predictor_no_recompile_on_batch_churn(self, tmp_path):
        """Satellite: standalone Predictor stops recompiling when batch
        size oscillates — same signature == cache hit."""
        prefix = self._export(tmp_path)
        pred = create_predictor(Config(prefix))
        cache = pred._exec_cache
        m0 = cache.stats()["misses"]
        for n in (1, 3, 1, 3, 1, 3, 7, 7, 7):
            pred.run([np.zeros((n, 6), np.float32)])
        s = cache.stats()
        assert s["misses"] - m0 == 3      # one compile per distinct shape
        assert s["hits"] >= 6
