"""nn layer tests with torch/numpy cross-checks
(pattern: reference unittests/test_layers.py + per-op tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import stable_uid
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestLinearConv:
    def test_linear_math(self):
        lin = nn.Linear(3, 2)
        w = np.arange(6).reshape(3, 2).astype(np.float32)
        b = np.array([1.0, -1.0], np.float32)
        lin.weight.set_value(w)
        lin.bias.set_value(b)
        x = np.array([[1.0, 2.0, 3.0]], np.float32)
        out = lin(paddle.to_tensor(x))
        np.testing.assert_allclose(out.numpy(), x @ w + b, rtol=1e-5)

    @pytest.mark.slow
    def test_conv2d_vs_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.rand(2, 3, 8, 8).astype(np.float32)
        w = np.random.rand(5, 3, 3, 3).astype(np.float32)
        b = np.random.rand(5).astype(np.float32)
        ours = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                        paddle.to_tensor(b), stride=2, padding=1).numpy()
        theirs = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=2, padding=1).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4, rtol=1e-4)

    def test_conv2d_groups_dilation(self):
        torch = pytest.importorskip("torch")
        x = np.random.rand(1, 4, 10, 10).astype(np.float32)
        w = np.random.rand(8, 2, 3, 3).astype(np.float32)
        ours = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                        groups=2, dilation=2, padding=2).numpy()
        theirs = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(w), groups=2, dilation=2,
            padding=2).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4, rtol=1e-4)

    def test_depthwise(self):
        torch = pytest.importorskip("torch")
        x = np.random.rand(1, 6, 8, 8).astype(np.float32)
        w = np.random.rand(6, 1, 3, 3).astype(np.float32)
        ours = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                        groups=6, padding=1).numpy()
        theirs = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(w), groups=6, padding=1).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4, rtol=1e-4)

    def test_conv2d_transpose(self):
        torch = pytest.importorskip("torch")
        x = np.random.rand(1, 4, 5, 5).astype(np.float32)
        w = np.random.rand(4, 3, 3, 3).astype(np.float32)
        ours = F.conv2d_transpose(paddle.to_tensor(x), paddle.to_tensor(w),
                                  stride=2, padding=1).numpy()
        theirs = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), stride=2, padding=1).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4, rtol=1e-4)


class TestPool:
    def test_max_avg_pool(self):
        torch = pytest.importorskip("torch")
        x = np.random.rand(2, 3, 8, 8).astype(np.float32)
        ours = F.max_pool2d(paddle.to_tensor(x), 2, 2).numpy()
        theirs = torch.nn.functional.max_pool2d(torch.tensor(x), 2, 2).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-6)
        ours = F.avg_pool2d(paddle.to_tensor(x), 3, 2, 1).numpy()
        theirs = torch.nn.functional.avg_pool2d(
            torch.tensor(x), 3, 2, 1, count_include_pad=False).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4)

    def test_adaptive_pool(self):
        torch = pytest.importorskip("torch")
        x = np.random.rand(2, 3, 7, 9).astype(np.float32)
        ours = F.adaptive_avg_pool2d(paddle.to_tensor(x), [3, 4]).numpy()
        theirs = torch.nn.functional.adaptive_avg_pool2d(
            torch.tensor(x), (3, 4)).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4)


class TestNorm:
    def test_batch_norm_train_eval(self):
        torch = pytest.importorskip("torch")
        x = np.random.rand(4, 3, 5, 5).astype(np.float32)
        ours_bn = nn.BatchNorm2D(3, momentum=0.9)
        theirs_bn = torch.nn.BatchNorm2d(3, momentum=0.1)  # torch: new*0.1
        out1 = ours_bn(paddle.to_tensor(x)).numpy()
        out2 = theirs_bn(torch.tensor(x)).detach().numpy()
        np.testing.assert_allclose(out1, out2, atol=1e-4)
        np.testing.assert_allclose(ours_bn._mean.numpy(),
                                   theirs_bn.running_mean.numpy(), atol=1e-5)
        # running_var follows the reference's *biased* batch-var convention
        # (batch_norm_op.cc:397), unlike torch's unbiased one.
        biased_var = x.var(axis=(0, 2, 3))
        np.testing.assert_allclose(ours_bn._variance.numpy(),
                                   0.9 * np.ones(3) + 0.1 * biased_var,
                                   atol=1e-5)
        ours_bn.eval()
        theirs_bn.eval()
        # align running stats before comparing eval outputs
        theirs_bn.running_var.data = torch.tensor(ours_bn._variance.numpy())
        np.testing.assert_allclose(
            ours_bn(paddle.to_tensor(x)).numpy(),
            theirs_bn(torch.tensor(x)).detach().numpy(), atol=1e-4)

    def test_layer_norm(self):
        torch = pytest.importorskip("torch")
        x = np.random.rand(2, 5, 8).astype(np.float32)
        ours = nn.LayerNorm(8)
        theirs = torch.nn.LayerNorm(8)
        np.testing.assert_allclose(
            ours(paddle.to_tensor(x)).numpy(),
            theirs(torch.tensor(x)).detach().numpy(), atol=1e-5)

    def test_group_norm(self):
        torch = pytest.importorskip("torch")
        x = np.random.rand(2, 6, 4, 4).astype(np.float32)
        ours = nn.GroupNorm(3, 6)
        theirs = torch.nn.GroupNorm(3, 6)
        np.testing.assert_allclose(
            ours(paddle.to_tensor(x)).numpy(),
            theirs(torch.tensor(x)).detach().numpy(), atol=1e-5)


class TestLosses:
    def test_cross_entropy_vs_torch(self):
        torch = pytest.importorskip("torch")
        logits = np.random.rand(4, 7).astype(np.float32)
        labels = np.array([0, 3, 6, 2])
        ours = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(labels)).numpy()
        theirs = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels)).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4)

    def test_cross_entropy_ignore_index(self):
        torch = pytest.importorskip("torch")
        logits = np.random.rand(4, 7).astype(np.float32)
        labels = np.array([0, -100, 6, -100])
        ours = F.cross_entropy(paddle.to_tensor(logits),
                               paddle.to_tensor(labels), ignore_index=-100).numpy()
        theirs = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels), ignore_index=-100).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4)

    def test_bce_with_logits(self):
        torch = pytest.importorskip("torch")
        z = np.random.randn(3, 4).astype(np.float32)
        y = np.random.rand(3, 4).astype(np.float32)
        ours = F.binary_cross_entropy_with_logits(
            paddle.to_tensor(z), paddle.to_tensor(y)).numpy()
        theirs = torch.nn.functional.binary_cross_entropy_with_logits(
            torch.tensor(z), torch.tensor(y)).numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4)

    def test_kl_smooth_l1(self):
        torch = pytest.importorskip("torch")
        a = np.log(np.random.rand(3, 4).astype(np.float32) + 0.1)
        b = np.random.rand(3, 4).astype(np.float32)
        ours = F.kl_div(paddle.to_tensor(a), paddle.to_tensor(b),
                        reduction="sum").numpy()
        theirs = torch.nn.functional.kl_div(
            torch.tensor(a), torch.tensor(b), reduction="sum").numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4)


class TestRNN:
    def test_lstm_vs_torch(self):
        torch = pytest.importorskip("torch")
        paddle.seed(1)
        ours = nn.LSTM(4, 6)
        theirs = torch.nn.LSTM(4, 6, batch_first=True)
        # copy our weights into torch
        sd = {k: v.numpy() for k, v in ours.state_dict().items()}
        with torch.no_grad():
            theirs.weight_ih_l0.copy_(torch.tensor(sd["cell_0_0.weight_ih"]))
            theirs.weight_hh_l0.copy_(torch.tensor(sd["cell_0_0.weight_hh"]))
            theirs.bias_ih_l0.copy_(torch.tensor(sd["cell_0_0.bias_ih"]))
            theirs.bias_hh_l0.copy_(torch.tensor(sd["cell_0_0.bias_hh"]))
        x = np.random.rand(2, 5, 4).astype(np.float32)
        out_o, (h_o, c_o) = ours(paddle.to_tensor(x))
        out_t, (h_t, c_t) = theirs(torch.tensor(x))
        np.testing.assert_allclose(out_o.numpy(), out_t.detach().numpy(), atol=1e-4)
        np.testing.assert_allclose(h_o.numpy(), h_t.detach().numpy(), atol=1e-4)

    def test_gru_shapes(self):
        gru = nn.GRU(3, 5, num_layers=2)
        out, h = gru(paddle.randn([2, 7, 3]))
        assert out.shape == [2, 7, 5]
        assert h.shape == [2, 2, 5]


class TestTransformer:
    def test_mha_self_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 5, 16])
        out = mha(x)
        assert out.shape == [2, 5, 16]

    def test_mha_mask(self):
        mha = nn.MultiHeadAttention(8, 2)
        x = paddle.randn([1, 4, 8])
        mask = paddle.tril(paddle.ones([4, 4], "bool"))
        out = mha(x, attn_mask=mask)
        assert out.shape == [1, 4, 8]

    @pytest.mark.slow
    def test_encoder_decoder(self):
        model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=2,
                               num_decoder_layers=2, dim_feedforward=32)
        src = paddle.randn([2, 6, 16])
        tgt = paddle.randn([2, 4, 16])
        out = model(src, tgt)
        assert out.shape == [2, 4, 16]


class TestLayerMechanics:
    def test_hooks(self):
        lin = nn.Linear(2, 2)
        calls = []
        h1 = lin.register_forward_pre_hook(lambda l, i: calls.append("pre"))
        h2 = lin.register_forward_post_hook(lambda l, i, o: calls.append("post"))
        lin(paddle.randn([1, 2]))
        assert calls == ["pre", "post"]
        h1.remove()
        h2.remove()
        lin(paddle.randn([1, 2]))
        assert calls == ["pre", "post"]

    def test_state_dict_roundtrip(self):
        a = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
        b = nn.Sequential(nn.Linear(3, 4), nn.ReLU(), nn.Linear(4, 2))
        b.set_state_dict(a.state_dict())
        x = paddle.randn([2, 3])
        np.testing.assert_allclose(a(x).numpy(), b(x).numpy())

    def test_train_eval_propagates(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        x = paddle.ones([4, 2])
        np.testing.assert_allclose(m[1](x).numpy(), x.numpy())

    def test_parameters_dedup(self):
        shared = nn.Linear(2, 2)
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = shared
                self.b = shared
            def forward(self, x):
                return self.b(self.a(x))
        assert len(M().parameters()) == 2  # weight+bias counted once


class TestOptimizers:
    def _quadratic(self, opt_fn, steps=120, atol=0.15):
        paddle.seed(0)
        w = paddle.to_tensor(np.array([5.0, -3.0], np.float32), stop_gradient=False)
        from paddle_tpu.core.tensor import Parameter
        p = Parameter(w._data)
        opt = opt_fn([p])
        for _ in range(steps):
            loss = ((p - paddle.to_tensor([1.0, 2.0])) ** 2).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
        np.testing.assert_allclose(p.numpy(), [1.0, 2.0], atol=atol)

    def test_sgd(self):
        import paddle_tpu.optimizer as optim
        self._quadratic(lambda ps: optim.SGD(0.1, parameters=ps))

    def test_momentum(self):
        import paddle_tpu.optimizer as optim
        self._quadratic(lambda ps: optim.Momentum(0.05, 0.9, parameters=ps))

    def test_adam(self):
        import paddle_tpu.optimizer as optim
        self._quadratic(lambda ps: optim.Adam(0.3, parameters=ps))

    def test_adamw(self):
        import paddle_tpu.optimizer as optim
        self._quadratic(lambda ps: optim.AdamW(0.3, parameters=ps,
                                               weight_decay=0.0))

    def test_rmsprop_lamb(self):
        import paddle_tpu.optimizer as optim
        self._quadratic(lambda ps: optim.RMSProp(0.1, parameters=ps))
        # Lamb's trust ratio scales the step by |w|/|update| — convergence on
        # a toy quadratic is asymptotic, so use a loose radius
        self._quadratic(lambda ps: optim.Lamb(0.1, lamb_weight_decay=0.0,
                                              parameters=ps), steps=600,
                        atol=0.5)

    @pytest.mark.slow
    def test_adam_vs_torch_trajectory(self):
        torch = pytest.importorskip("torch")
        import paddle_tpu.optimizer as optim
        from paddle_tpu.core.tensor import Parameter
        w0 = np.array([1.5, -2.0], np.float32)
        p = Parameter(w0.copy())
        opt = optim.Adam(0.1, parameters=[p])
        tp = torch.nn.Parameter(torch.tensor(w0.copy()))
        topt = torch.optim.Adam([tp], lr=0.1)
        for _ in range(10):
            (p * p).sum().backward()
            opt.step()
            opt.clear_grad()
            tloss = (tp * tp).sum()
            topt.zero_grad()
            tloss.backward()
            topt.step()
        np.testing.assert_allclose(p.numpy(), tp.detach().numpy(), atol=1e-4)

    def test_grad_clip_global_norm(self):
        import paddle_tpu.optimizer as optim
        from paddle_tpu.core.tensor import Parameter
        p = Parameter(np.array([1.0], np.float32))
        clip = paddle.ClipGradByGlobalNorm(0.5)
        opt = optim.SGD(1.0, parameters=[p], grad_clip=clip)
        (p * 100.0).sum().backward()
        opt.step()
        # grad 100 clipped to 0.5 -> p = 1 - 0.5
        np.testing.assert_allclose(p.numpy(), [0.5], atol=1e-5)

    def test_lr_scheduler(self):
        import paddle_tpu.optimizer as optim
        from paddle_tpu.optimizer import lr as lr_mod
        from paddle_tpu.core.tensor import Parameter
        sched = lr_mod.StepDecay(0.1, step_size=2, gamma=0.5)
        p = Parameter(np.array([1.0], np.float32))
        opt = optim.SGD(sched, parameters=[p])
        assert abs(opt.get_lr() - 0.1) < 1e-9
        sched.step(); sched.step()
        assert abs(opt.get_lr() - 0.05) < 1e-9

    def test_optimizer_state_roundtrip(self):
        import paddle_tpu.optimizer as optim
        from paddle_tpu.core.tensor import Parameter
        p = Parameter(np.array([1.0, 2.0], np.float32))
        opt = optim.Adam(0.1, parameters=[p])
        (p * p).sum().backward()
        opt.step(); opt.clear_grad()
        state = opt.state_dict()
        p2 = Parameter(np.array([1.0, 2.0], np.float32))
        opt2 = optim.Adam(0.1, parameters=[p2])
        opt2.set_state_dict(state)
        np.testing.assert_allclose(
            np.asarray(opt2._state[stable_uid(p2)]["moment1"]),
            np.asarray(opt._state[stable_uid(p)]["moment1"]))


class TestRound3Losses:
    """warpctc alias, hinge_embedding/rank/dice losses, ctc_greedy_decoder
    (reference: warpctc_op.cc, rank_loss_op.cc, fluid layers dice_loss,
    ctc_greedy_decoder)."""

    def test_hinge_embedding_loss(self):
        out = F.hinge_embedding_loss(
            paddle.to_tensor(np.array([0.5, 2.0], np.float32)),
            paddle.to_tensor(np.array([1.0, -1.0], np.float32)),
            reduction="none")
        np.testing.assert_allclose(out.numpy(), [0.5, 0.0])

    def test_rank_loss(self):
        rl = F.rank_loss(paddle.to_tensor(np.array([1.0], np.float32)),
                         paddle.to_tensor(np.array([2.0], np.float32)),
                         paddle.to_tensor(np.array([1.0], np.float32)))
        np.testing.assert_allclose(rl.numpy(),
                                   np.log1p(np.exp(1.0)) - 1.0, rtol=1e-6)

    def test_dice_loss_perfect_prediction(self):
        x = np.zeros((2, 3, 4), np.float32)
        y = np.zeros((2, 3, 1), np.int32)
        for i in range(2):
            for j in range(3):
                c = (i + j) % 4
                x[i, j, c] = 1.0
                y[i, j, 0] = c
        d = F.dice_loss(paddle.to_tensor(x), paddle.to_tensor(y))
        assert float(d.numpy()) < 1e-3

    def test_ctc_greedy_decoder(self):
        lp = np.full((5, 1, 3), -5.0, np.float32)
        for t, c in enumerate([1, 1, 0, 2, 2]):
            lp[t, 0, c] = 0.0
        dec, nl = F.ctc_greedy_decoder(paddle.to_tensor(lp), blank=0)
        assert nl.numpy().tolist() == [2]
        assert dec.numpy()[0, :2].tolist() == [1, 2]

    def test_warpctc_matches_ctc_loss_none(self):
        rng = np.random.RandomState(0)
        lp = np.log(np.random.RandomState(0).dirichlet(
            np.ones(4), size=(6, 2)).astype(np.float32))
        labels = np.array([[1, 2], [3, 1]], np.int64)
        il = np.array([6, 6], np.int64)
        ll = np.array([2, 2], np.int64)
        a = F.warpctc(paddle.to_tensor(lp), paddle.to_tensor(labels),
                      input_length=paddle.to_tensor(il),
                      label_length=paddle.to_tensor(ll))
        b = F.ctc_loss(paddle.to_tensor(lp), paddle.to_tensor(labels),
                       paddle.to_tensor(il), paddle.to_tensor(ll),
                       reduction="none")
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-6)
