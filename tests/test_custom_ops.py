"""Custom-op extension path (reference: framework/custom_operator.cc:511,
utils/cpp_extension/) + the Pallas greedy-NMS kernel."""
import shutil

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.ops as ops
from paddle_tpu.ops import custom


class TestRegisterOp:
    def test_register_and_autograd(self):
        if not hasattr(ops, "_test_cube3"):
            custom.register_op("_test_cube3", lambda a: a * a * a)
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        y = ops._test_cube3(x)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [12.0], rtol=1e-6)

    def test_duplicate_name_raises(self):
        with pytest.raises(ValueError, match="already"):
            custom.register_op("matmul", lambda a: a)


class TestPallasNMS:
    @pytest.mark.slow
    def test_matches_scan_reference(self):
        from paddle_tpu.ops.detection import (_pairwise_iou,
                                              _greedy_nms_mask)
        rng = np.random.RandomState(0)
        k = 32
        boxes = rng.rand(k, 4).astype(np.float32) * 10
        boxes[:, 2:] = boxes[:, :2] + 1 + boxes[:, 2:]
        scores = rng.rand(k).astype(np.float32)
        kept_ref, order, top_s = _greedy_nms_mask(
            jnp.asarray(boxes), jnp.asarray(scores), 0.5, 0.05, k)
        iou = _pairwise_iou(jnp.asarray(boxes)[order],
                            jnp.asarray(boxes)[order])
        valid = (top_s > 0.05).astype(jnp.int32)
        kept_pl = custom.pallas_greedy_nms(iou, valid, jnp.asarray([0.5]),
                                           interpret=True)
        np.testing.assert_array_equal(
            np.asarray(kept_ref).astype(np.int32), np.asarray(kept_pl))


@pytest.mark.skipif(shutil.which("g++") is None, reason="no toolchain")
class TestCppOp:
    def test_host_cpp_op(self, tmp_path):
        src = r'''
extern "C" void double_plus_one(const float* in, float* out, long n) {
  for (long i = 0; i < n; ++i) out[i] = in[i] * 2.0f + 1.0f;
}
'''
        if not hasattr(ops, "_test_dpo"):
            custom.register_cpp_op("_test_dpo", src,
                                   fn_name="double_plus_one",
                                   build_dir=str(tmp_path))
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        out = ops._test_dpo(x)
        np.testing.assert_allclose(out.numpy(), [3.0, 5.0])
