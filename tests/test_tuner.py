"""paddle_tpu.tuner: autotuner search, winner-cache integrity, and the
tuned flash-attention/NMS kernel paths.

Covers the ISSUE-P11 satellite guarantees:
- odd sequence lengths stay numerically exact for any sane block config
  (the wrapper pads; the kernel core rejects non-dividing blocks),
- a corrupt/truncated/version-mismatched winner cache is ignored with a
  warning and retuned — never crashes, never silently applies bad blocks.
"""
import json
import os
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu.tuner as tuner
from paddle_tpu.tuner import space, store
from paddle_tpu.ops.pallas_attention import (DEFAULT_BLOCK, _fa_fwd_with_lse,
                                             _sanitize_block,
                                             flash_attention)


@pytest.fixture()
def tune_cache(tmp_path, monkeypatch):
    """Point the winner cache at a fresh dir and reset all memo tiers."""
    monkeypatch.setenv("PADDLE_TPU_TUNE_CACHE", str(tmp_path))
    tuner.clear_memo()
    yield tmp_path
    tuner.clear_memo()


def _dense_ref(q, k, v, causal):
    qb, kb, vb = (np.moveaxis(x, 2, 1) for x in (q, k, v))
    s = np.einsum("bhqd,bhkd->bhqk", qb, kb) / np.sqrt(q.shape[-1])
    if causal:
        tri = np.tril(np.ones(s.shape[-2:], bool))
        s = np.where(tri, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.moveaxis(np.einsum("bhqk,bhkd->bhqd", p, vb), 1, 2)


class TestOddLengthTails:
    """Satellite 1: seq_len not divisible by the chosen block must pad
    correctly (wrapper) or fail loudly (core) — never drop tail rows."""

    @pytest.mark.parametrize("s", [17, 33, 100, 130, 255])
    @pytest.mark.parametrize("causal", [False, True])
    def test_wrapper_matches_dense_for_odd_lengths(self, s, causal,
                                                   tune_cache):
        rng = np.random.RandomState(s)
        q = rng.randn(1, s, 2, 16).astype(np.float32)
        k = rng.randn(1, s, 2, 16).astype(np.float32)
        v = rng.randn(1, s, 2, 16).astype(np.float32)
        out, _ = flash_attention(jnp.array(q), jnp.array(k), jnp.array(v),
                                 causal=causal)
        np.testing.assert_allclose(np.asarray(out),
                                   _dense_ref(q, k, v, causal),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("bq,bk", [(16, 48), (48, 16), (32, 48)])
    def test_explicit_nondividing_blocks_still_exact(self, bq, bk,
                                                     tune_cache):
        # 100 rounds to 112; neither 48 nor the sanitized 48 divides it,
        # so the wrapper must pad up to the block grid and mask the tail
        s = 100
        rng = np.random.RandomState(7)
        q = rng.randn(1, s, 1, 16).astype(np.float32)
        k = rng.randn(1, s, 1, 16).astype(np.float32)
        v = rng.randn(1, s, 1, 16).astype(np.float32)
        out, _ = flash_attention(jnp.array(q), jnp.array(k), jnp.array(v),
                                 causal=True, block_q=bq, block_k=bk)
        np.testing.assert_allclose(np.asarray(out),
                                   _dense_ref(q, k, v, True),
                                   atol=2e-5, rtol=2e-5)

    def test_core_rejects_nondividing_blocks(self):
        q = jnp.zeros((2, 64, 8))
        with pytest.raises(ValueError, match="must divide"):
            _fa_fwd_with_lse(q, q, q, False, 1.0, 48, 16, True, 64)
        with pytest.raises(ValueError, match="must divide"):
            _fa_fwd_with_lse(q, q, q, False, 1.0, 16, 48, True, 64)

    def test_sanitize_block(self):
        assert _sanitize_block(128, 100) == 112   # clamp to ceil16(len)
        assert _sanitize_block(100, 4096) == 112  # round up to 16-multiple
        assert _sanitize_block(0, 4096) == DEFAULT_BLOCK
        assert _sanitize_block(-5, 64) == 64
        assert _sanitize_block(16, 7) == 16       # floor at one sublane


class TestWinnerStoreIntegrity:
    """Satellite 3: bad caches warn + retune, never crash."""

    def _winners_path(self, tmp):
        platform = jax.devices()[0].platform
        return os.path.join(str(tmp), f"winners-{platform}.json")

    def test_corrupt_file_ignored_with_warning(self, tune_cache):
        with open(self._winners_path(tune_cache), "w") as f:
            f.write("{ this is not json !!")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            cfg = tuner.get_flash_blocks(999, 999, 32, "float32", False)
        assert cfg is None
        assert any("corrupt" in str(x.message) for x in w)

    def test_truncated_file_ignored_with_warning(self, tune_cache):
        with open(self._winners_path(tune_cache), "w") as f:
            f.write('{"version": 1, "entries": {"flash_fwd|cpu')
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            cfg = tuner.get_flash_blocks(999, 999, 32, "float32", False)
        assert cfg is None
        assert any("corrupt" in str(x.message) for x in w)

    def test_version_mismatch_ignored_with_warning(self, tune_cache):
        key = tuner.flash_key(999, 999, 32, "float32", False)
        with open(self._winners_path(tune_cache), "w") as f:
            json.dump({"version": tuner.CACHE_VERSION + 1,
                       "entries": {key: {"config": {"block_q": 32,
                                                    "block_k": 32}}}}, f)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            cfg = tuner.get_flash_blocks(999, 999, 32, "float32", False)
        assert cfg is None
        assert any("version" in str(x.message) for x in w)

    def test_malformed_entries_dropped_good_kept(self, tune_cache):
        key = tuner.flash_key(999, 999, 32, "float32", False)
        with open(self._winners_path(tune_cache), "w") as f:
            json.dump({"version": tuner.CACHE_VERSION,
                       "platform": "cpu",
                       "entries": {key: {"config": {"block_q": 32,
                                                    "block_k": 64}},
                                   "bad1": "not a dict",
                                   "bad2": {"no_config": True}}}, f)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            cfg = tuner.get_flash_blocks(999, 999, 32, "float32", False)
        assert cfg == (32, 64)
        assert any("malformed" in str(x.message) for x in w)

    def test_record_after_corruption_recovers(self, tune_cache):
        path = self._winners_path(tune_cache)
        with open(path, "w") as f:
            f.write("garbage")
        key = tuner.flash_key(999, 999, 32, "float32", False)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            tuner.record_winner(key, {"block_q": 64, "block_k": 64})
        tuner.clear_memo()
        assert tuner.get_flash_blocks(999, 999, 32, "float32",
                                      False) == (64, 64)
        # the rewritten file is valid versioned JSON again
        with open(path) as f:
            data = json.load(f)
        assert data["version"] == tuner.CACHE_VERSION

    def test_kernel_path_never_crashes_on_bad_cache(self, tune_cache):
        with open(self._winners_path(tune_cache), "w") as f:
            f.write("\x00\x01 binary trash")
        rng = np.random.RandomState(0)
        q = rng.randn(1, 32, 1, 16).astype(np.float32)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out, _ = flash_attention(jnp.array(q), jnp.array(q),
                                     jnp.array(q), causal=False)
        np.testing.assert_allclose(np.asarray(out),
                                   _dense_ref(q, q, q, False),
                                   atol=2e-5, rtol=2e-5)


class TestResolutionTiers:
    def test_disk_winner_used_by_kernel(self, tune_cache):
        s, d = 100, 16
        key = tuner.flash_key(s, s, d, "float32", True)
        tuner.record_winner(key, {"block_q": 32, "block_k": 64})
        tuner.clear_memo()
        assert tuner.get_flash_blocks(s, s, d, "float32", True) == (32, 64)
        rng = np.random.RandomState(1)
        q = rng.randn(1, s, 2, d).astype(np.float32)
        out, _ = flash_attention(jnp.array(q), jnp.array(q), jnp.array(q),
                                 causal=True)
        np.testing.assert_allclose(np.asarray(out),
                                   _dense_ref(q, q, q, True),
                                   atol=2e-5, rtol=2e-5)

    def test_lengths_canonicalized_to_16(self, tune_cache):
        assert tuner.flash_key(4095, 4095, 64, "bfloat16", True,
                               platform="tpu") \
            == tuner.flash_key(4096, 4096, 64, "bfloat16", True,
                               platform="tpu")

    def test_defaults_table_ships_bench_winner(self, tune_cache):
        # the committed defaults must cover the GPT-small S=4096 bench
        # shape on TPU (acceptance criterion: cold fleet never tunes it)
        st = store.WinnerStore("tpu", directory=str(tune_cache))
        cfg = st.lookup("flash_fwd|tpu|bfloat16|d64|q4096|k4096|c1")
        assert cfg and cfg["block_q"] % 16 == 0 and cfg["block_k"] % 16 == 0

    def test_disk_shadows_defaults(self, tune_cache):
        key = "flash_fwd|tpu|bfloat16|d64|q4096|k4096|c1"
        st = store.WinnerStore("tpu", directory=str(tune_cache))
        shipped = st.lookup(key)
        st.record(key, {"block_q": 256, "block_k": 256})
        st2 = store.WinnerStore("tpu", directory=str(tune_cache))
        assert st2.lookup(key) == {"block_q": 256, "block_k": 256}
        assert shipped != st2.lookup(key)

    def test_memo_avoids_disk_after_first_lookup(self, tune_cache,
                                                 monkeypatch):
        key = tuner.flash_key(64, 64, 16, "float32", False)
        tuner.record_winner(key, {"block_q": 32, "block_k": 32})
        tuner.clear_memo()
        assert tuner.get_flash_blocks(64, 64, 16, "float32",
                                      False) == (32, 32)
        calls = {"n": 0}
        real = store.store_for

        def counting(platform):
            calls["n"] += 1
            return real(platform)
        monkeypatch.setattr(tuner.store, "store_for", counting)
        monkeypatch.setattr(tuner, "store_for", counting)
        for _ in range(5):
            assert tuner.get_flash_blocks(64, 64, 16, "float32",
                                          False) == (32, 32)
        assert calls["n"] == 0       # memo tier served every repeat


class TestCandidateSpace:
    def test_vmem_pruning(self):
        # kv=12288 at d=128 f32 leaves <1 MiB after the resident K/V,
        # so big score blocks must be pruned while small ones survive
        cands = space.flash_candidates(12288, 12288, 128, itemsize=4)
        assert cands and (512, 512) not in cands
        for bq, bk in cands:
            assert space.flash_vmem_bytes(bq, bk, 12288, 128,
                                          4) <= space.VMEM_BUDGET

    def test_require_divides(self):
        cands = space.flash_candidates(96, 96, 16, require_divides=True)
        for bq, bk in cands:
            assert 96 % bq == 0 and 96 % bk == 0

    def test_all_blocks_sublane_multiples(self):
        for bq, bk in space.flash_candidates(1000, 1000, 64):
            assert bq % 16 == 0 and bk % 16 == 0

    def test_never_empty(self):
        assert space.flash_candidates(8, 8, 8) == [(16, 16)]


class TestAutotune:
    def test_search_records_and_reloads(self, tune_cache):
        res = tuner.autotune_flash(2, 64, 64, 16, trials=2)
        assert res["block_q"] % 16 == 0 and res["block_k"] % 16 == 0
        assert res["us"] > 0 and res["results"]
        tuner.clear_memo()
        assert tuner.get_flash_blocks(64, 64, 16, "float32", False) \
            == (res["block_q"], res["block_k"])

    def test_ring_search_respects_divisor_constraint(self, tune_cache):
        res = tuner.autotune_flash(1, 96, 96, 16, trials=1, ring=True)
        assert 96 % res["block_q"] == 0 and 96 % res["block_k"] == 0


class TestRingBlocks:
    def test_tuned_divisor_used(self, tune_cache):
        from paddle_tpu.distributed.fleet.sequence_parallel import \
            _ring_blocks
        key = tuner.flash_key(256, 256, 16, "float32", False, ring=True)
        tuner.record_winner(key, {"block_q": 64, "block_k": 64})
        tuner.clear_memo()
        assert _ring_blocks(256, 16, jnp.float32) == (64, 64)

    def test_nondividing_winner_discarded(self, tune_cache):
        from paddle_tpu.distributed.fleet.sequence_parallel import \
            _ring_blocks
        key = tuner.flash_key(256, 256, 16, "float32", False, ring=True)
        tuner.record_winner(key, {"block_q": 48, "block_k": 48})
        tuner.clear_memo()
        # 48 doesn't divide 256: fall back to the historical default
        assert _ring_blocks(256, 16, jnp.float32) == (128, 128)


class TestNMSUnroll:
    def test_unroll_preserves_result(self, tune_cache):
        from paddle_tpu.ops.custom import pallas_greedy_nms
        rng = np.random.RandomState(3)
        iou = jnp.array(rng.rand(16, 16).astype(np.float32))
        valid = jnp.ones((16,), jnp.int32)
        thr = jnp.array([0.5], jnp.float32)
        base = np.asarray(pallas_greedy_nms(iou, valid, thr,
                                            interpret=True, unroll=1))
        for u in (2, 4, 8):
            out = np.asarray(pallas_greedy_nms(iou, valid, thr,
                                               interpret=True, unroll=u))
            np.testing.assert_array_equal(base, out)

    def test_tuned_unroll_from_cache(self, tune_cache):
        from paddle_tpu.ops.custom import _nms_unroll
        tuner.record_winner(tuner.nms_key(16), {"unroll": 4})
        tuner.clear_memo()
        assert _nms_unroll(16) == 4
        # non-divisor winners are rejected
        tuner.record_winner(tuner.nms_key(18), {"unroll": 4})
        tuner.clear_memo()
        assert _nms_unroll(18) == 1
