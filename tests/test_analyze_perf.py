"""Budget test: a full-repo analyzer run (the whole AST tier — eight
rules including PTA008's recompile-risk call-graph walk — baseline diff
included) must stay interactive. The issue pins the ceiling at 30 s; in
practice the run is well under 5 s on CI hardware, so a breach means an
algorithmic regression (e.g. the call-graph resolver losing its
memoization), not noise. The trace tier (PTA009/PTA010) compiles code and
is excluded from the default selection, so it does not count against this
budget.
"""
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_full_repo_analyze_under_30s():
    start = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "paddle_tpu", "tools"],
        cwd=REPO, capture_output=True, text=True)
    elapsed = time.monotonic() - start
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 30.0, f"analyze took {elapsed:.1f}s (budget 30s)"
