"""Budget test: a full-repo analyzer run (the whole AST tier — ten
rules including PTA008's recompile-risk call-graph walk and PTA013's
committed-winner VMEM sweep, baseline diff included) must stay
interactive.

Measured 2026-08: ~16.5 s on the CI container (the call-graph builds
and PTA013's standalone `tuner/space.py` load dominate), so the
ceiling is pinned at 45 s — ~2.7x headroom for slower hardware while
still failing fast on an algorithmic regression (e.g. the call-graph
resolver losing its memoization, or a rule importing jax). The trace
tier (PTA009/PTA010/PTA012/PTA014) compiles code and is excluded from
the default selection, so it does not count against this budget.
"""
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_full_repo_analyze_under_45s():
    start = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "paddle_tpu", "tools"],
        cwd=REPO, capture_output=True, text=True)
    elapsed = time.monotonic() - start
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 45.0, f"analyze took {elapsed:.1f}s (budget 45s)"
