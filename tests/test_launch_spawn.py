"""Launcher + spawn tests (reference analogs: test_fleet_launch_*.sh driven
by dist_test.sh; test_spawn.py). A real 2-process CPU launch runs
init_parallel_env -> jax.distributed -> a cross-process allgather."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest


TRAIN_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ.pop("JAX_PLATFORMS", None)
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, {repo!r})
    import paddle_tpu.distributed as dist

    env = dist.init_parallel_env()
    assert dist.get_world_size() == 2, dist.get_world_size()
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    out = multihost_utils.process_allgather(
        jnp.asarray([float(dist.get_rank() + 1)]))
    assert out.reshape(-1).tolist() == [1.0, 2.0], out
    print("RANK", dist.get_rank(), "OK", flush=True)
""")


@pytest.fixture
def train_script(tmp_path):
    path = tmp_path / "train.py"
    path.write_text(TRAIN_SCRIPT.format(repo="/root/repo"))
    return str(path)


class TestLauncher:
    @pytest.mark.slow
    def test_two_process_launch(self, train_script, tmp_path):
        log_dir = str(tmp_path / "logs")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--start_port", "12455",
             "--log_dir", log_dir, train_script],
            cwd="/root/repo", capture_output=True, text=True, timeout=180)
        logs = ""
        for rank in range(2):
            with open(os.path.join(log_dir, f"workerlog.{rank}")) as f:
                logs += f.read()
        assert proc.returncode == 0, (proc.stderr, logs)
        assert "RANK 0 OK" in logs and "RANK 1 OK" in logs

    @pytest.mark.slow
    def test_failing_child_tears_down(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import os, sys, time\n"
            "if os.environ['PADDLE_TRAINER_ID'] == '1':\n"
            "    sys.exit(3)\n"
            "time.sleep(60)\n")
        proc = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nproc_per_node", "2", "--start_port", "12475", str(bad)],
            cwd="/root/repo", capture_output=True, text=True, timeout=60)
        assert proc.returncode == 3
        assert "exited with code 3" in proc.stderr

    def test_get_cluster_endpoints(self):
        from paddle_tpu.distributed.launch import get_cluster
        eps = get_cluster(["10.0.0.1", "10.0.0.2"], 2, 6070)
        assert eps == ["10.0.0.1:6070", "10.0.0.1:6071",
                       "10.0.0.2:6070", "10.0.0.2:6071"]


def _spawn_target(value):
    import os
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    assert os.environ["PADDLE_TRAINERS_NUM"] == "2"
    if value != 42:
        raise ValueError("bad arg plumb")
    # write a marker so the parent can verify both ranks ran
    open(f"/tmp/spawn_ok_{rank}", "w").write("ok")


def _spawn_failer():
    import os
    if os.environ["PADDLE_TRAINER_ID"] == "1":
        raise RuntimeError("boom from rank 1")


class TestSpawn:
    @pytest.mark.slow
    def test_spawn_two_procs(self):
        import paddle_tpu.distributed as dist
        for r in range(2):
            try:
                os.remove(f"/tmp/spawn_ok_{r}")
            except FileNotFoundError:
                pass
        dist.spawn(_spawn_target, args=(42,), nprocs=2,
                   start_port=12495)
        for r in range(2):
            assert os.path.exists(f"/tmp/spawn_ok_{r}")

    def test_spawn_surfaces_child_error(self):
        import paddle_tpu.distributed as dist
        with pytest.raises(RuntimeError, match="boom from rank 1"):
            dist.spawn(_spawn_failer, nprocs=2, start_port=12515)
