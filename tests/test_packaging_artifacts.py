"""Distribution hygiene: audit artifacts never ship; tuner defaults do.

run_tests.py writes ``analysis.sarif`` / ``trace_audit.json`` at the
repo root (gitignored working files). This builds a real sdist and wheel
through ``setuptools.build_meta`` — with those artifacts present on
disk, the worst case — and asserts the file lists exclude them, and that
the committed ``paddle_tpu/tuner/default_winners.json`` IS packaged (the
cold-fleet autotuner tier depends on it shipping).
"""
import os
import subprocess
import sys
import tarfile
import zipfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: gitignored audit/bench artifacts — plus review-process residue
#: (REVIEW.md/VERDICT.md) — that must never reach a distribution
FORBIDDEN = ("analysis.sarif", "trace_audit.json", "trace_audit_full.json",
             ".pytest_shard_0.log", "REVIEW.md", "VERDICT.md")

_BUILD = r"""
import os, sys
from setuptools import build_meta
out = sys.argv[1]
kind = sys.argv[2]
if kind == "sdist":
    print(build_meta.build_sdist(out))
else:
    print(build_meta.build_wheel(out))
"""


@pytest.fixture(scope="module")
def dists(tmp_path_factory):
    """Build sdist + wheel once, in a subprocess (build_meta assumes it
    owns cwd/argv), with sentinel audit artifacts planted at the root."""
    out = tmp_path_factory.mktemp("dist")
    planted = []
    for name in FORBIDDEN:
        path = os.path.join(REPO, name)
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write("{}")
            planted.append(path)
    # setuptools writes build/ + egg-info into the project root; remember
    # which did not exist so only OUR side effects get cleaned up
    side_effects = [p for p in
                    (os.path.join(REPO, "build"),
                     os.path.join(REPO, "paddle_tpu.egg-info"))
                    if not os.path.exists(p)]
    script = out / "build.py"
    script.write_text(_BUILD)
    try:
        names = {}
        for kind in ("sdist", "wheel"):
            proc = subprocess.run(
                [sys.executable, str(script), str(out), kind],
                capture_output=True, text=True, cwd=REPO, timeout=300)
            assert proc.returncode == 0, proc.stderr[-3000:]
            names[kind] = os.path.join(
                str(out), proc.stdout.strip().splitlines()[-1])
    finally:
        import shutil
        for path in planted:
            try:
                os.unlink(path)
            except OSError:
                pass
        for path in side_effects:
            shutil.rmtree(path, ignore_errors=True)
    sdist_names = tarfile.open(names["sdist"]).getnames()
    wheel_names = zipfile.ZipFile(names["wheel"]).namelist()
    return sdist_names, wheel_names


@pytest.mark.slow
class TestDistributionContents:
    def test_no_audit_artifact_in_sdist(self, dists):
        sdist_names, _ = dists
        leaked = [n for n in sdist_names
                  if os.path.basename(n) in FORBIDDEN]
        assert leaked == [], f"audit artifacts in sdist: {leaked}"

    def test_no_audit_artifact_in_wheel(self, dists):
        _, wheel_names = dists
        leaked = [n for n in wheel_names
                  if os.path.basename(n) in FORBIDDEN]
        assert leaked == [], f"audit artifacts in wheel: {leaked}"

    def test_no_sarif_or_log_anywhere(self, dists):
        sdist_names, wheel_names = dists
        bad = [n for n in sdist_names + wheel_names
               if n.endswith((".sarif", ".log"))]
        assert bad == []

    def test_tuner_defaults_ship_in_wheel(self, dists):
        _, wheel_names = dists
        assert any(n.endswith("paddle_tpu/tuner/default_winners.json")
                   for n in wheel_names), \
            "default_winners.json missing from wheel — cold installs " \
            "would lose the committed autotuner tier"

    def test_bench_audit_baseline_not_in_wheel(self, dists):
        # repo-root CI fixture, not a runtime file
        _, wheel_names = dists
        assert not any(n.endswith("bench_audit_baseline.json")
                       for n in wheel_names)
