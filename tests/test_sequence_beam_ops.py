"""Sequence family over the masked-ragged (padded + lengths) convention +
beam-search ops, numpy-checked (reference: operators/sequence_ops/,
beam_search_op.cc, gather_tree_op.cc, ctc_align_op.cc,
edit_distance_op.cc; test style: unittests/op_test.py numpy references)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.ops as ops


def T(x):
    return paddle.to_tensor(np.asarray(x))


class TestSequenceOps:
    def test_sequence_mask(self):
        out = ops.sequence_mask(T([2, 0, 3]), maxlen=4).numpy()
        np.testing.assert_array_equal(
            out, [[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]])

    def test_pad_unpad_roundtrip(self):
        flat = np.arange(12, dtype=np.float32).reshape(6, 2)
        lens = np.array([2, 1, 3])
        padded, L = ops.sequence_pad(T(flat), 0.0, maxlen=3, length=T(lens))
        p = padded.numpy()
        np.testing.assert_allclose(p[0, :2], flat[:2])
        np.testing.assert_allclose(p[1, :1], flat[2:3])
        np.testing.assert_allclose(p[2], flat[3:6])
        assert (p[0, 2] == 0).all() and (p[1, 1:] == 0).all()
        back = ops.sequence_unpad(padded, T(lens)).numpy()
        np.testing.assert_allclose(back, flat)

    def test_sequence_pool_types(self):
        x = np.array([[[1.], [2.], [9.]],
                      [[4.], [7.], [9.]]], np.float32)
        lens = np.array([2, 3])
        assert ops.sequence_pool(T(x), "sum", T(lens)).numpy().tolist() == \
            [[3.0], [20.0]]
        np.testing.assert_allclose(
            ops.sequence_pool(T(x), "average", T(lens)).numpy(),
            [[1.5], [20 / 3]], rtol=1e-6)
        assert ops.sequence_pool(T(x), "max", T(lens)).numpy().tolist() == \
            [[2.0], [9.0]]
        assert ops.sequence_last_step(T(x), T(lens)).numpy().tolist() == \
            [[2.0], [9.0]]
        assert ops.sequence_first_step(T(x), T(lens)).numpy().tolist() == \
            [[1.0], [4.0]]

    def test_sequence_softmax_masks_padding(self):
        x = np.array([[1.0, 1.0, 99.0]], np.float32)
        out = ops.sequence_softmax(T(x), T(np.array([2]))).numpy()
        np.testing.assert_allclose(out, [[0.5, 0.5, 0.0]], atol=1e-6)

    def test_sequence_reverse(self):
        x = np.array([[1, 2, 3, 0], [4, 5, 6, 7]], np.float32)
        out = ops.sequence_reverse(T(x), T(np.array([3, 4]))).numpy()
        np.testing.assert_array_equal(out, [[3, 2, 1, 0], [7, 6, 5, 4]])

    def test_sequence_expand(self):
        x = np.array([[1.0], [2.0]], np.float32)
        out = ops.sequence_expand(T(x), T(np.array([2, 3]))).numpy()
        np.testing.assert_allclose(out.ravel(), [1, 1, 2, 2, 2])

    def test_sequence_concat(self):
        a = np.array([[1, 2, 0]], np.float32)
        b = np.array([[7, 8, 9]], np.float32)
        data, total = ops.sequence_concat(
            [T(a), T(b)], [T(np.array([2])), T(np.array([3]))])
        assert total.numpy().tolist() == [5]
        np.testing.assert_allclose(data.numpy()[0, :5], [1, 2, 7, 8, 9])

    def test_sequence_erase(self):
        x = np.array([[3, 5, 3, 7], [5, 5, 1, 0]], np.int64)
        out, nl = ops.sequence_erase(T(x), [5], T(np.array([4, 3])))
        assert nl.numpy().tolist() == [3, 1]
        np.testing.assert_array_equal(out.numpy()[0, :3], [3, 3, 7])
        assert out.numpy()[1, 0] == 1

    def test_sequence_enumerate(self):
        x = np.array([[1, 2, 3]], np.int64)
        out = ops.sequence_enumerate(T(x), 2, pad_value=0).numpy()
        np.testing.assert_array_equal(out[0], [[1, 2], [2, 3], [3, 0]])

    def test_sequence_conv_matches_manual(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 4, 3).astype(np.float32)
        w = rng.randn(9, 5).astype(np.float32)
        out = ops.sequence_conv(T(x), T(w), context_length=3,
                                context_start=-1).numpy()
        # manual: ctx(t) = [x[t-1], x[t], x[t+1]] zero-padded
        padded = np.pad(x, [(0, 0), (1, 1), (0, 0)])
        ctx = np.concatenate([padded[:, :-2], padded[:, 1:-1],
                              padded[:, 2:]], axis=-1)
        np.testing.assert_allclose(out, ctx @ w, rtol=1e-4, atol=1e-5)

    def test_im2sequence(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        out = ops.im2sequence(T(x), (2, 2), strides=(2, 2)).numpy()
        assert out.shape == (4, 4)
        np.testing.assert_allclose(out[0], [0, 1, 4, 5])


class TestBeamOps:
    def test_gather_tree(self):
        # reference unit test values (test_gather_tree_op.py)
        ids = np.array([[[2, 2], [6, 1]], [[3, 9], [6, 1]], [[0, 1], [9, 0]]],
                       np.int64)
        parents = np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]],
                            [[0, 0], [0, 1]]], np.int64)
        out = ops.gather_tree(T(ids), T(parents)).numpy()
        expect = np.array([[[2, 2], [1, 6]], [[3, 3], [6, 1]],
                           [[0, 1], [9, 0]]], np.int64)
        np.testing.assert_array_equal(out, expect)

    def test_beam_search_step(self):
        # 1 batch row, 2 beams, vocab 4
        pre_ids = T(np.array([[1, 2]], np.int64))
        pre_scores = T(np.array([[0.0, -1.0]], np.float32))
        scores = np.full((1, 2, 4), -np.inf, np.float32)
        scores[0, 0] = [-1.0, -0.1, -5.0, -3.0]     # beam 0 candidates
        scores[0, 1] = [-2.0, -0.2, -6.0, -4.0]     # beam 1 candidates
        tok, sc, parent = ops.beam_search(
            pre_ids, pre_scores, None, T(scores), beam_size=2, end_id=3)
        # best two: beam0/tok1 (-0.1), beam1/tok1 (-0.2)
        np.testing.assert_array_equal(tok.numpy(), [[1, 1]])
        np.testing.assert_allclose(sc.numpy(), [[-0.1, -0.2]], rtol=1e-6)
        np.testing.assert_array_equal(parent.numpy(), [[0, 1]])

    def test_beam_search_finished_beam_propagates(self):
        pre_ids = T(np.array([[3, 2]], np.int64))   # beam 0 finished (end=3)
        pre_scores = T(np.array([[-0.5, -1.0]], np.float32))
        scores = np.zeros((1, 2, 4), np.float32) - 10.0
        scores[0, 1, 1] = -0.7
        tok, sc, parent = ops.beam_search(
            pre_ids, pre_scores, None, T(scores), beam_size=2, end_id=3)
        assert tok.numpy()[0, 0] == 3 and abs(sc.numpy()[0, 0] + 0.5) < 1e-6

    def test_ctc_align(self):
        x = np.array([[0, 1, 1, 0, 2, 2, 0]], np.int32)
        out, nl = ops.ctc_align(T(x), blank=0, merge_repeated=True)
        assert nl.numpy().tolist() == [2]
        np.testing.assert_array_equal(out.numpy()[0, :2], [1, 2])

    def test_edit_distance(self):
        hyp = np.array([[1, 2, 3, 0]], np.int64)
        ref = np.array([[1, 3, 3]], np.int64)
        d, n = ops.edit_distance(T(hyp), T(ref), normalized=False,
                                 input_length=T(np.array([3])),
                                 label_length=T(np.array([3])))
        assert d.numpy()[0, 0] == 1.0
        d2, _ = ops.edit_distance(T(hyp), T(ref), normalized=True,
                                  input_length=T(np.array([3])),
                                  label_length=T(np.array([3])))
        np.testing.assert_allclose(d2.numpy()[0, 0], 1 / 3, rtol=1e-6)
