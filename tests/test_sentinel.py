"""Unit tests for the numerical-anomaly sentinel (paddle_tpu/sentinel/):
detector statistics, policy ladder, fused step guard, quarantine dumps,
health-stamped rollback, TrainEpochRange health awareness, the hardened
FaultInjector spec parser, and GradScaler telemetry/state round-trip."""
import json
import math
import os

import numpy as np
import pytest
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, sentinel
from paddle_tpu import optimizer as optim
from paddle_tpu.core import monitor
from paddle_tpu.incubate.checkpoint import (
    TrainEpochRange, save_sharded, write_health_stamp, read_health_stamp,
    HEALTH_STAMP_FILE)
from paddle_tpu.sentinel import (
    AnomalyReport, CheckpointRollback, LossSpikeDetector, PolicyEngine,
    Sentinel, SentinelConfig, StepGuard, quarantine_batch, read_quarantine)
from paddle_tpu.utils.resilience import FaultInjector


@pytest.fixture(autouse=True)
def _clean_sentinel_stats():
    for prefix in ("sentinel.", "amp."):
        for k in list(monitor.stats_with_prefix(prefix)):
            monitor.default_registry().reset(k)
    yield


# -- detector -----------------------------------------------------------------

class TestLossSpikeDetector:
    def test_warmup_never_spikes(self):
        d = LossSpikeDetector(warmup_steps=10, z_threshold=1.0)
        for i in range(10):
            z, spike = d.update(100.0 if i == 5 else 1.0)
            assert not spike
        assert d.warmed_up

    def test_spike_after_warmup_upward_only(self):
        d = LossSpikeDetector(warmup_steps=5, z_threshold=4.0)
        for v in [1.0, 1.1, 0.9, 1.05, 0.95, 1.0]:
            d.update(v)
        z, spike = d.update(50.0)
        assert spike and z > 4.0
        # a crash *downward* is good news, not divergence
        z, spike = d.update(0.0)
        assert not spike

    def test_spike_excluded_from_statistics(self):
        d = LossSpikeDetector(warmup_steps=3, z_threshold=3.0)
        for v in [1.0, 1.1, 0.9, 1.0]:
            d.update(v)
        mean_before = d.mean
        _, spike = d.update(500.0)
        assert spike
        assert d.mean == mean_before  # the anomaly didn't drag the baseline

    def test_non_finite_is_inf_spike_without_stat_update(self):
        d = LossSpikeDetector(warmup_steps=2)
        d.update(1.0)
        mean_before = d.mean
        z, spike = d.update(float("nan"))
        assert spike and math.isinf(z)
        assert d.mean == mean_before
        z, spike = d.update(float("inf"))
        assert spike and math.isinf(z)

    def test_reset_and_state_roundtrip(self):
        d = LossSpikeDetector(warmup_steps=2)
        for v in [1.0, 2.0, 3.0]:
            d.update(v)
        state = d.state_dict()
        d2 = LossSpikeDetector(warmup_steps=2)
        d2.load_state_dict(state)
        assert d2.mean == d.mean and d2.std == d.std and d2.warmed_up
        d.reset()
        assert d.mean is None and not d.warmed_up

    def test_validation(self):
        with pytest.raises(ValueError, match="alpha"):
            LossSpikeDetector(alpha=0.0)
        with pytest.raises(ValueError, match="z_threshold"):
            LossSpikeDetector(z_threshold=-1.0)


# -- policy -------------------------------------------------------------------

class TestPolicyEngine:
    def test_ladder_rungs(self):
        p = PolicyEngine(("skip_step", "rollback", "halt"), tolerance=1)
        assert p.decide(1) == "skip_step"
        assert p.decide(2) == "rollback"
        assert p.decide(3) == "halt"
        assert p.decide(99) == "halt"  # clamps at the last rung

    def test_tolerance_stretches_rungs(self):
        p = PolicyEngine(("skip_step", "halt"), tolerance=3)
        assert [p.decide(n) for n in range(1, 8)] == \
            ["skip_step"] * 3 + ["halt"] * 4

    def test_config_validation(self):
        with pytest.raises(ValueError, match="unknown sentinel action"):
            SentinelConfig(ladder=("skip_step", "explode"))
        with pytest.raises(ValueError, match="at least one"):
            SentinelConfig(ladder=())
        with pytest.raises(ValueError, match="check_every"):
            SentinelConfig(check_every=0)
        with pytest.raises(ValueError, match="tolerance"):
            SentinelConfig(tolerance=0)


# -- guard --------------------------------------------------------------------

class TestStepGuard:
    def test_finite_probe(self):
        g = StepGuard()
        finite, loss = g.probe([jnp.ones(4), jnp.zeros((2, 2))],
                               jnp.float32(1.5))
        assert finite and loss == pytest.approx(1.5)

    def test_nan_grad_flips_flag(self):
        g = StepGuard()
        finite, _ = g.probe([jnp.ones(4),
                             jnp.array([1.0, jnp.nan])], jnp.float32(1.0))
        assert not finite

    def test_inf_loss_flips_flag(self):
        g = StepGuard()
        finite, _ = g.probe([jnp.ones(4)], jnp.float32(jnp.inf))
        assert not finite

    def test_grads_only_probe(self):
        g = StepGuard()
        finite, loss = g.probe([jnp.ones(3)])
        assert finite and loss is None

    def test_one_host_sync_per_probe(self):
        before = monitor.stat_get("sentinel.host_syncs")
        g = StepGuard()
        for _ in range(5):
            g.probe([jnp.ones(4)], jnp.float32(1.0))
        assert monitor.stat_get("sentinel.host_syncs") == before + 5

    def test_check_every(self):
        g = StepGuard(check_every=3)
        assert [g.should_check(s) for s in range(7)] == \
            [True, False, False, True, False, False, True]
        with pytest.raises(ValueError, match="check_every"):
            StepGuard(check_every=0)


# -- quarantine ---------------------------------------------------------------

class TestQuarantine:
    def test_dump_and_read_roundtrip(self, tmp_path):
        root = str(tmp_path / "q")
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
        y = np.ones(2, np.float32)
        entry = quarantine_batch(root, 7, ([x], [y]), ["non_finite"],
                                 loss=float("nan"), z=None)
        assert entry and os.path.basename(entry) == "step_7"
        meta, arrays = read_quarantine(entry)
        assert meta["step"] == 7 and meta["reasons"] == ["non_finite"]
        assert meta["loss"] is None or math.isnan(meta["loss"])
        np.testing.assert_array_equal(arrays["x0"], x.numpy())
        np.testing.assert_array_equal(arrays["y0"], y)

    def test_metadata_only_when_no_batch(self, tmp_path):
        root = str(tmp_path / "q")
        entry = quarantine_batch(root, 3, None, ["loss_spike(z=9.00)"],
                                 loss=123.0, z=9.0)
        meta, arrays = read_quarantine(entry)
        assert meta["z"] == 9.0 and arrays == {}
        assert not os.path.exists(os.path.join(entry, "inputs.npz"))

    def test_cap_drops_and_counts(self, tmp_path):
        root = str(tmp_path / "q")
        for step in range(3):
            quarantine_batch(root, step, None, ["r"], max_entries=2)
        entries = sorted(n for n in os.listdir(root)
                         if n.startswith("step_"))
        assert entries == ["step_0", "step_1"]
        assert monitor.stat_get("sentinel.quarantine_dropped") == 1

    def test_unset_root_is_noop(self):
        assert quarantine_batch(None, 0, None, ["r"]) is None


# -- health stamps + rollback -------------------------------------------------

class TestHealthStamps:
    def test_write_read_roundtrip(self, tmp_path):
        d = str(tmp_path / "ck")
        save_sharded({"a": jnp.arange(3.0)}, d)
        write_health_stamp(d, False, step=12, reason="nan grads")
        stamp = read_health_stamp(d)
        assert stamp["healthy"] is False
        assert stamp["step"] == 12 and stamp["reason"] == "nan grads"

    def test_missing_stamp_reads_healthy(self, tmp_path):
        d = str(tmp_path / "ck")
        save_sharded({"a": jnp.arange(3.0)}, d)
        assert read_health_stamp(d) == {"healthy": True}

    def test_corrupt_stamp_reads_healthy(self, tmp_path):
        d = tmp_path / "ck"
        d.mkdir()
        (d / HEALTH_STAMP_FILE).write_text("{not json")
        assert read_health_stamp(str(d))["healthy"] is True
        (d / HEALTH_STAMP_FILE).write_text("[1, 2]")
        assert read_health_stamp(str(d))["healthy"] is True


def _lin_job(tmp_path, path="snaps"):
    paddle.seed(7)
    net = nn.Linear(4, 2)
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    rb = CheckpointRollback(str(tmp_path / path), model=net, optimizer=opt)
    return net, opt, rb


def _train_step(net, opt):
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    loss = paddle.mean(net(x) ** 2)
    loss.backward()
    opt.step()
    opt.clear_grad()


class TestCheckpointRollback:
    def test_restore_newest_healthy(self, tmp_path):
        net, opt, rb = _lin_job(tmp_path)
        rb.snapshot(1)
        w1 = net.weight.numpy().copy()
        _train_step(net, opt)
        rb.snapshot(2)
        w2 = net.weight.numpy().copy()
        _train_step(net, opt)
        assert rb.restore_newest_healthy() == 2
        np.testing.assert_array_equal(net.weight.numpy(), w2)
        assert not np.array_equal(w1, w2)

    def test_unhealthy_stamped_newest_is_skipped(self, tmp_path):
        """The ISSUE's core case: newest snapshot is integrity-VALID but
        health-stamped unhealthy — restore must fall back past it."""
        net, opt, rb = _lin_job(tmp_path)
        rb.snapshot(1)
        w1 = net.weight.numpy().copy()
        _train_step(net, opt)
        rb.snapshot(2)
        rb.mark_unhealthy(2, reason="divergence detected after save")
        # the unhealthy snapshot still passes checksum verification
        from paddle_tpu.incubate.checkpoint import verify_checkpoint
        verify_checkpoint(os.path.join(rb.path, "snap_2"))
        assert rb.restore_newest_healthy() == 1
        np.testing.assert_array_equal(net.weight.numpy(), w1)

    def test_stampless_snapshot_restorable(self, tmp_path):
        """Backward compat: pre-sentinel snapshots carry no stamp at all."""
        net, opt, rb = _lin_job(tmp_path)
        rb.snapshot(1)
        os.remove(os.path.join(rb.path, "snap_1", HEALTH_STAMP_FILE))
        assert rb.restore_newest_healthy() == 1

    def test_corrupt_newest_falls_back(self, tmp_path):
        net, opt, rb = _lin_job(tmp_path)
        rb.snapshot(1)
        _train_step(net, opt)
        rb.snapshot(2)
        shard = [f for f in os.listdir(os.path.join(rb.path, "snap_2"))
                 if f.startswith("shards_")][0]
        full = os.path.join(rb.path, "snap_2", shard)
        with open(full, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            b = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([b[0] ^ 0xFF]))
        with pytest.warns(UserWarning, match="not intact"):
            assert rb.restore_newest_healthy() == 1

    def test_gc_keeps_unhealthy_out_of_budget(self, tmp_path):
        net, opt, rb = _lin_job(tmp_path)
        rb.keep_last = 2
        rb.snapshot(1)
        rb.snapshot(2, healthy=False, reason="bad")
        rb.snapshot(3)
        rb.snapshot(4)
        rb.snapshot(5)
        # healthy budget is {4, 5}; unhealthy 2 is retained (not counted)
        assert rb.steps() == [2, 4, 5]

    def test_nothing_usable_returns_none(self, tmp_path):
        net, opt, rb = _lin_job(tmp_path)
        assert rb.restore_newest_healthy() is None
        rb.snapshot(1, healthy=False)
        assert rb.restore_newest_healthy() is None


class TestTrainEpochRangeHealthAware:
    def test_restore_skips_unhealthy_stamped_epoch(self, tmp_path):
        paddle.seed(11)
        net = nn.Linear(4, 2)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        r = TrainEpochRange(5, "jobH", model=net, optimizer=opt,
                            checkpoint_path=str(tmp_path / "auto"))
        weights = {}
        for epoch in [0, 1, 2]:
            _train_step(net, opt)
            r.save(epoch)
            weights[epoch] = net.weight.numpy().copy()
        r.mark_unhealthy(2, reason="sentinel: diverged during epoch 3")
        net2 = nn.Linear(4, 2)
        opt2 = optim.SGD(learning_rate=0.1, parameters=net2.parameters())
        with pytest.warns(UserWarning, match="stamped unhealthy"):
            r2 = TrainEpochRange(5, "jobH", model=net2, optimizer=opt2,
                                 checkpoint_path=str(tmp_path / "auto"))
        assert r2.restored_epoch == 1
        np.testing.assert_array_equal(net2.weight.numpy(), weights[1])

    def test_restore_without_stamps_unchanged(self, tmp_path):
        paddle.seed(11)
        net = nn.Linear(4, 2)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        r = TrainEpochRange(5, "jobN", model=net, optimizer=opt,
                            checkpoint_path=str(tmp_path / "auto"))
        _train_step(net, opt)
        r.save(0)
        r2 = TrainEpochRange(5, "jobN", model=nn.Linear(4, 2),
                             checkpoint_path=str(tmp_path / "auto"))
        assert r2.restored_epoch == 0


# -- fault-injector parser hardening ------------------------------------------

class TestFaultInjectorParser:
    def test_whitespace_is_stripped(self):
        fi = FaultInjector(" grads : 2 : nan , loss:1:nan ")
        assert fi.armed("grads") and fi.armed("loss")
        assert fi.fire("loss") == "nan"
        assert fi.fire("grads") is None and fi.fire("grads") == "nan"

    def test_empty_segment_rejected(self):
        with pytest.raises(ValueError, match="bad PADDLE_TPU_FAULT_SPEC"):
            FaultInjector("grads::nan")
        with pytest.raises(ValueError, match="bad PADDLE_TPU_FAULT_SPEC"):
            FaultInjector(":1:nan")
        with pytest.raises(ValueError, match="bad PADDLE_TPU_FAULT_SPEC"):
            FaultInjector("grads:1:")

    def test_occurrence_zero_rejected(self):
        with pytest.raises(ValueError, match="1-based"):
            FaultInjector("grads:0:nan")

    def test_duplicate_site_occurrence_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            FaultInjector("step:3:crash,step:3:raise")

    def test_same_site_different_occurrence_ok(self):
        fi = FaultInjector("step:1:nan,step:3:crash")
        assert fi.fire("step") == "nan"
        assert fi.fire("step") is None


# -- GradScaler telemetry + state round-trip ----------------------------------

class TestGradScalerSatellite:
    def _scaler_after_inf(self):
        net = nn.Linear(4, 2)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                       decr_every_n_nan_or_inf=1)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        loss = paddle.mean(net(x) ** 2)
        scaled = scaler.scale(loss)
        scaled.backward()
        for p in opt._parameter_list:
            if p._grad is not None:
                p._grad = jnp.full_like(p._grad, jnp.inf)
        scaler.step(opt)
        scaler.update()
        return scaler

    def test_found_inf_counter_and_scale_gauge(self):
        before = monitor.stat_get("amp.found_inf_steps")
        scaler = self._scaler_after_inf()
        assert monitor.stat_get("amp.found_inf_steps") == before + 1
        assert monitor.stat_get("amp.loss_scale") == scaler._scale == 512.0

    def test_state_dict_emits_both_key_styles(self):
        scaler = paddle.amp.GradScaler(init_loss_scaling=8.0)
        scaler._good_steps = 5
        scaler._bad_steps = 2
        state = scaler.state_dict()
        assert state["good_steps"] == state["incr_count"] == 5
        assert state["bad_steps"] == state["decr_count"] == 2
        assert state["use_dynamic_loss_scaling"] is True
        assert state["found_inf"] is False

    def test_roundtrip_restores_counters(self):
        a = paddle.amp.GradScaler(init_loss_scaling=8.0, incr_ratio=3.0,
                                  decr_ratio=0.25, incr_every_n_steps=7)
        a._good_steps, a._bad_steps = 6, 1
        b = paddle.amp.GradScaler()
        b.load_state_dict(a.state_dict())
        assert b._scale == 8.0 and b._incr_ratio == 3.0
        assert b._decr_ratio == 0.25 and b._incr_every_n == 7
        assert b._good_steps == 6 and b._bad_steps == 1
        # counter continuity: one more good step triggers the increase
        # exactly where the pre-restore scaler would have
        b._found_inf = False
        b.update()
        assert b._good_steps == 0 and b._scale == 24.0

    def test_reference_key_style_loads(self):
        b = paddle.amp.GradScaler()
        b.load_state_dict({"scale": 16.0, "incr_count": 3, "decr_count": 1,
                           "use_dynamic_loss_scaling": False})
        assert b._scale == 16.0 and b._good_steps == 3
        assert b._bad_steps == 1 and b._dynamic is False


# -- the Sentinel end-to-end (in-process) -------------------------------------

def _sentinel_job(tmp_path, **cfg_kw):
    paddle.seed(3)
    net = nn.Linear(4, 2)
    opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
    rb = CheckpointRollback(str(tmp_path / "snaps"), model=net,
                            optimizer=opt)
    cfg_kw.setdefault("warmup_steps", 1000)  # only test NaN paths
    s = Sentinel(SentinelConfig(**cfg_kw), optimizer=opt, rollback=rb)
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = paddle.to_tensor(np.zeros((4, 2), np.float32))

    def step(poison=False):
        loss = paddle.mean((net(x) - y) ** 2)
        loss.backward()
        if poison:
            sentinel.poison_grads(opt)
        s.observe(loss=loss, batch=([x], [y]))
        opt.step()
        opt.clear_grad()
        return s.last_report

    return net, opt, rb, s, step


class TestSentinel:
    def test_healthy_steps_approve_and_count_syncs(self, tmp_path):
        net, opt, rb, s, step = _sentinel_job(tmp_path)
        syncs0 = monitor.stat_get("sentinel.host_syncs")
        for _ in range(4):
            r = step()
            assert not r.anomalous
        # exactly ONE host sync per guarded healthy step
        assert monitor.stat_get("sentinel.host_syncs") == syncs0 + 4

    def test_nan_grads_skip_update_params_unchanged(self, tmp_path):
        net, opt, rb, s, step = _sentinel_job(tmp_path)
        step()
        w = net.weight.numpy().copy()
        r = step(poison=True)
        assert r.anomalous and r.action == "skip_step"
        assert r.reasons == ["non_finite"]
        np.testing.assert_array_equal(net.weight.numpy(), w)
        assert monitor.stat_get("sentinel.nan_steps") == 1
        assert monitor.stat_get("sentinel.skipped_steps") == 1
        # a healthy step resets the consecutive count
        r = step()
        assert not r.anomalous and s._consecutive == 0

    def test_full_ladder_escalation(self, tmp_path):
        net, opt, rb, s, step = _sentinel_job(
            tmp_path, quarantine_dir=str(tmp_path / "q"))
        step()
        rb.snapshot(1)
        w_good = net.weight.numpy().copy()
        assert step(poison=True).action == "skip_step"
        r = step(poison=True)
        assert r.action == "quarantine_batch"
        assert os.path.isdir(str(tmp_path / "q" / "step_2"))
        r = step(poison=True)
        assert r.action == "rollback" and r.rolled_back_to == 1
        np.testing.assert_array_equal(net.weight.numpy(), w_good)
        assert monitor.stat_get("sentinel.rollbacks") == 1

    def test_halt_exits_with_divergence_code(self, tmp_path):
        net, opt, rb, s, step = _sentinel_job(
            tmp_path, ladder=("halt",))
        step()
        with pytest.raises(SystemExit) as ei:
            step(poison=True)
        assert ei.value.code == sentinel.DIVERGENCE_EXIT_CODE == 119
        assert monitor.stat_get("sentinel.halts") == 1

    def test_rollback_without_adapter_degrades_to_skip(self, tmp_path):
        paddle.seed(3)
        net = nn.Linear(4, 2)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        s = Sentinel(SentinelConfig(ladder=("rollback",),
                                    warmup_steps=1000), optimizer=opt)
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        loss = paddle.mean(net(x) ** 2)
        loss.backward()
        sentinel.poison_grads(opt)
        w = net.weight.numpy().copy()
        with pytest.warns(UserWarning, match="no rollback adapter"):
            opt.step()
        np.testing.assert_array_equal(net.weight.numpy(), w)

    def test_check_every_amortizes_probes(self, tmp_path):
        net, opt, rb, s, step = _sentinel_job(tmp_path, check_every=3)
        checks0 = monitor.stat_get("sentinel.checks")
        for _ in range(6):
            step()
        assert monitor.stat_get("sentinel.checks") == checks0 + 2

    def test_lr_rescale_on_rollback(self, tmp_path):
        net, opt, rb, s, step = _sentinel_job(
            tmp_path, ladder=("rollback",), lr_rescale=0.5)
        step()
        rb.snapshot(1)
        step(poison=True)
        assert opt.get_lr() == pytest.approx(0.05)

    def test_feed_loss_spike_detection(self, tmp_path):
        net, opt, rb, s, step = _sentinel_job(tmp_path, warmup_steps=3,
                                              z_threshold=4.0)
        for v in [1.0, 1.1, 0.9, 1.0, 1.05]:
            assert s.feed_loss(v) is None
        report = s.feed_loss(100.0)
        assert report is not None and report.action == "skip_step"
        assert "loss_spike" in report.reasons[0]
        assert monitor.stat_get("sentinel.spike_steps") == 1

    def test_feed_loss_no_double_count_after_approve_step(self, tmp_path):
        net, opt, rb, s, step = _sentinel_job(tmp_path)
        step(poison=True)
        assert s._consecutive == 1
        # hapi flow: the callback feeds the same step's (NaN) loss after
        # the in-step probe already escalated it — must not count twice
        assert s.feed_loss(float("nan")) is None
        assert s._consecutive == 1

    def test_fault_injected_nan_at_exact_step(self, tmp_path, monkeypatch):
        from paddle_tpu.utils import resilience
        monkeypatch.setenv("PADDLE_TPU_FAULT_SPEC", "grads:2:nan")
        resilience._reset_fault_injector_for_tests()
        try:
            net, opt, rb, s, step = _sentinel_job(tmp_path)
            assert not step().anomalous           # fire 1: no rule
            r = step()                            # fire 2: poisons grads
            assert r.anomalous and r.reasons == ["non_finite"]
            assert not step().anomalous           # fire 3: clean again
        finally:
            monkeypatch.delenv("PADDLE_TPU_FAULT_SPEC")
            resilience._reset_fault_injector_for_tests()

    def test_detach_restores_unguarded_step(self, tmp_path):
        net, opt, rb, s, step = _sentinel_job(tmp_path)
        checks0 = monitor.stat_get("sentinel.checks")
        step()
        s.detach()
        step()
        assert monitor.stat_get("sentinel.checks") == checks0 + 1


# -- monitor helper -----------------------------------------------------------

def test_stats_with_prefix():
    monitor.stat_add("sentinel.x", 2)
    monitor.stat_add("sentinel.y", 1)
    monitor.stat_add("other.z", 9)
    view = monitor.stats_with_prefix("sentinel.")
    assert view["sentinel.x"] == 2 and view["sentinel.y"] == 1
    assert "other.z" not in view
    monitor.default_registry().reset("other.z")


# -- AnomalyGuardCallback through Model.fit -----------------------------------

class TestAnomalyGuardCallback:
    def _fit(self, tmp_path, spec=None, monkeypatch=None, epochs=2):
        from paddle_tpu.utils import resilience
        from paddle_tpu.hapi.callbacks import AnomalyGuardCallback
        from paddle_tpu.static import InputSpec
        if spec is not None:
            monkeypatch.setenv("PADDLE_TPU_FAULT_SPEC", spec)
        resilience._reset_fault_injector_for_tests()
        try:
            paddle.seed(5)
            net = nn.Linear(4, 2)
            model = paddle.Model(net, inputs=[InputSpec([None, 4], "float32")],
                                 labels=[InputSpec([None, 2], "float32")])
            opt = optim.SGD(learning_rate=0.05,
                            parameters=net.parameters())
            model.prepare(opt, nn.loss.MSELoss())
            cb = AnomalyGuardCallback(save_dir=str(tmp_path / "guard"))
            xs = np.random.RandomState(0).randn(16, 4).astype("float32")
            ys = np.zeros((16, 2), np.float32)
            model.fit(list(zip(xs, ys)), batch_size=4, epochs=epochs,
                      verbose=0, callbacks=[cb])
            return net, model, cb
        finally:
            if spec is not None:
                monkeypatch.delenv("PADDLE_TPU_FAULT_SPEC")
            resilience._reset_fault_injector_for_tests()

    def test_clean_fit_snapshots_healthy(self, tmp_path):
        net, model, cb = self._fit(tmp_path)
        snaps = cb.rollback.steps()
        assert snaps, "epoch-end snapshots expected"
        for s in snaps:
            d = os.path.join(cb.rollback.path, f"snap_{s}")
            assert read_health_stamp(d)["healthy"] is True

    def test_injected_nan_step_is_skipped_and_training_finishes(
            self, tmp_path, monkeypatch):
        net, model, cb = self._fit(tmp_path, spec="grads:3:nan",
                                   monkeypatch=monkeypatch)
        assert np.all(np.isfinite(net.weight.numpy()))
        assert cb.sentinel.anomalies >= 1
        assert monitor.stat_get("sentinel.nan_steps") >= 1

    def test_anomalous_epoch_snapshot_stamped_unhealthy(self, tmp_path,
                                                        monkeypatch):
        net, model, cb = self._fit(tmp_path, spec="grads:2:nan",
                                   monkeypatch=monkeypatch, epochs=1)
        snaps = cb.rollback.steps()
        assert snaps
        d = os.path.join(cb.rollback.path, f"snap_{snaps[-1]}")
        assert read_health_stamp(d)["healthy"] is False
