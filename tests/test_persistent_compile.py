"""Persistent fleet-wide compilation cache (serving/cache.py).

The acceptance property: a SECOND process starting against a warm cache
root performs zero XLA compiles for the predictor signatures the first
process already served — the serialized-executable tier loads whole AOT
executables without even issuing a compile request, and any remaining
jit compile request is served by JAX's persistent compilation cache.

Plus the integrity story, mirroring the tuner cache: corrupt entries are
dropped with a warning and recompiled, never crash, never serve garbage.
"""
import json
import os
import pickle
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core import monitor as _mon
from paddle_tpu.serving import cache as cache_mod
from paddle_tpu.serving.cache import (ExecutableCache,
                                      PersistentExecutableStore,
                                      enable_persistent_compilation,
                                      persistent_root, persistent_store)
from paddle_tpu.static import InputSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _export_artifact(tmp_path):
    paddle.seed(7)

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(6, 4)

        def forward(self, x):
            return nn.functional.relu(self.fc(x))

    prefix = str(tmp_path / "persist_net")
    paddle.jit.save(Net(), prefix,
                    input_spec=[InputSpec([None, 6], "float32", "x")])
    return prefix


@pytest.fixture()
def persist_env(tmp_path, monkeypatch):
    """Fresh persistence root + reset process-wide cache state; restores
    the jax compilation-cache config afterwards so later tests are
    unaffected."""
    import jax
    saved = {k: getattr(jax.config, k) for k in
             ("jax_compilation_cache_dir",
              "jax_persistent_cache_min_compile_time_secs",
              "jax_persistent_cache_min_entry_size_bytes")}
    monkeypatch.setenv("PADDLE_TPU_COMPILE_CACHE", str(tmp_path))
    cache_mod._reset_persistence_for_tests()
    cache_mod._reset_default_cache_for_tests()
    yield tmp_path
    cache_mod._reset_persistence_for_tests()
    cache_mod._reset_default_cache_for_tests()
    for k, v in saved.items():
        jax.config.update(k, v)


# ---------------------------------------------------------------------------
# the zero-compile warm-start acceptance test: two real processes

_CHILD = r"""
import json, os, sys
import numpy as np
import jax
from jax import monitoring

requests = []
hits = []
monitoring.register_event_listener(lambda name, **kw: (
    requests.append(1) if name == "/jax/compilation_cache/compile_requests_use_cache"
    else hits.append(1) if name == "/jax/compilation_cache/cache_hits" else None))

from paddle_tpu.core import monitor as _mon
from paddle_tpu.inference import Config, create_predictor

prefix = sys.argv[1]
pred = create_predictor(Config(prefix))
x = np.ones((3, 6), np.float32)
out1 = pred.run([x])[0]
out2 = pred.run([x])[0]          # second call: in-memory hit
assert np.array_equal(out1, out2)
print(json.dumps({
    "out_sum": float(out1.sum()),
    "compile_requests": len(requests),
    "xla_cache_hits": len(hits),
    "disk_hits": int(_mon.stat_get("serving.executable_cache.disk_hits")),
    "disk_writes": int(_mon.stat_get("serving.executable_cache.disk_writes")),
    "compile_fn_calls": int(_mon.stat_get("jit.cache_misses")),
}))
"""


def _run_child(prefix, cache_root, tmp_path):
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TPU_COMPILE_CACHE=str(cache_root),
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    proc = subprocess.run([sys.executable, str(script), prefix],
                          capture_output=True, text=True, timeout=300,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-3000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_warm_start_performs_zero_xla_compiles(tmp_path):
    prefix = _export_artifact(tmp_path)
    cache_root = tmp_path / "compile-cache"

    cold = _run_child(prefix, cache_root, tmp_path)
    # cold start: the predictor signature compiled once and was persisted
    assert cold["compile_fn_calls"] >= 1
    assert cold["disk_writes"] >= 1
    assert cold["disk_hits"] == 0

    warm = _run_child(prefix, cache_root, tmp_path)
    # warm start: the serialized executable loaded — compile_fn never ran
    assert warm["compile_fn_calls"] == 0
    assert warm["disk_hits"] >= 1
    # and every jit compile request that DID happen (internal utility
    # ops) was served by the persistent XLA cache: zero backend compiles
    assert warm["compile_requests"] == warm["xla_cache_hits"]
    # same numbers out of both processes
    assert warm["out_sum"] == cold["out_sum"]


# ---------------------------------------------------------------------------
# in-process: store round-trip, corruption tolerance, fold + counters

class TestPersistentExecutableStore:
    def _compiled(self, mul=2.0):
        import jax
        import jax.numpy as jnp
        return jax.jit(lambda x: x * mul).lower(
            jnp.zeros((4,), jnp.float32)).compile()

    def test_round_trip(self, tmp_path):
        import jax.numpy as jnp
        store = PersistentExecutableStore(str(tmp_path))
        assert store.save("k1", self._compiled()) is True
        exe = store.load("k1")
        assert exe is not None
        np.testing.assert_allclose(
            np.asarray(exe(jnp.arange(4, dtype=jnp.float32))),
            [0.0, 2.0, 4.0, 6.0])

    def test_missing_is_silent_miss(self, tmp_path):
        store = PersistentExecutableStore(str(tmp_path))
        assert store.load("nope") is None

    def test_corrupt_entry_warns_and_misses(self, tmp_path):
        store = PersistentExecutableStore(str(tmp_path))
        store.save("k1", self._compiled())
        path = store._path("k1")
        with open(path, "wb") as f:
            f.write(b"\x00garbage not a pickle")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert store.load("k1") is None
        assert any("unreadable" in str(x.message) for x in w)
        # the bad file was removed so the rewritten entry loads cleanly
        assert not os.path.exists(path)
        store.save("k1", self._compiled())
        assert store.load("k1") is not None

    def test_truncated_pickle_warns_and_misses(self, tmp_path):
        store = PersistentExecutableStore(str(tmp_path))
        store.save("k1", self._compiled())
        path = store._path("k1")
        blob = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(blob[:len(blob) // 3])
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert store.load("k1") is None
        assert any("unreadable" in str(x.message) for x in w)

    def test_version_and_platform_partition_keys(self, tmp_path):
        # same key under a different store version must hash differently
        store = PersistentExecutableStore(str(tmp_path))
        p1 = store._path("k1")
        old = cache_mod._STORE_VERSION
        try:
            cache_mod._STORE_VERSION = old + 1
            p2 = store._path("k1")
        finally:
            cache_mod._STORE_VERSION = old
        assert p1 != p2

    def test_jit_wrapper_silently_stays_memory_only(self, tmp_path):
        import jax
        store = PersistentExecutableStore(str(tmp_path))
        assert store.save("k1", jax.jit(lambda x: x)) is False
        assert os.listdir(tmp_path) == [] if os.path.isdir(tmp_path) \
            else True


class TestCacheDiskTier:
    def test_get_or_compile_uses_disk_tier(self, persist_env):
        import jax
        import jax.numpy as jnp
        enable_persistent_compilation()
        cache = ExecutableCache()
        calls = {"n": 0}

        def compile_fn():
            calls["n"] += 1
            return jax.jit(lambda x: x + 1).lower(
                jnp.zeros((2,), jnp.float32)).compile()

        cache.get_or_compile("key-a", compile_fn, persist_key="key-a")
        assert calls["n"] == 1
        # a FRESH in-memory cache (new process stand-in) loads from disk
        cache2 = ExecutableCache()
        exe = cache2.get_or_compile("key-a", compile_fn,
                                    persist_key="key-a")
        assert calls["n"] == 1           # compile_fn not called again
        np.testing.assert_allclose(
            np.asarray(exe(jnp.zeros((2,), jnp.float32))), [1.0, 1.0])

    def test_no_persist_key_no_disk(self, persist_env):
        import jax
        import jax.numpy as jnp
        enable_persistent_compilation()
        cache = ExecutableCache()
        cache.get_or_compile(
            "key-b", lambda: jax.jit(lambda x: x).lower(
                jnp.zeros((2,), jnp.float32)).compile())
        exe_dir = os.path.join(persistent_root(), "executables")
        assert not os.path.isdir(exe_dir) or os.listdir(exe_dir) == []

    def test_persistence_off_without_env(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_COMPILE_CACHE", raising=False)
        cache_mod._reset_persistence_for_tests()
        try:
            assert persistent_root() is None
            assert persistent_store() is None
        finally:
            cache_mod._reset_persistence_for_tests()


class TestSharedDefaultCacheAndCounters:
    def test_llm_decoder_defaults_to_process_cache(self):
        from paddle_tpu.serving.cache import default_cache
        from paddle_tpu.serving.llm.decode import GPTStaticDecoder
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM

        model = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=16, num_layers=1, num_heads=2,
            max_position_embeddings=32))
        dec = GPTStaticDecoder(model)
        assert dec.exec_cache is default_cache()

    def test_engine_callable_defaults_to_process_cache(self):
        from paddle_tpu.serving import Engine, EngineConfig
        from paddle_tpu.serving.cache import default_cache

        eng = Engine(lambda x: x * 2,
                     EngineConfig(max_batch=4, max_batch_delay=0.01))
        try:
            assert eng.cache is default_cache()
            # key embeds the fn object, not a recyclable id()
            key_fn = eng._model_key[1]
            assert callable(key_fn)
        finally:
            eng.drain(timeout=10)

    def test_counters_published_to_default_registry(self):
        reg = _mon.default_registry()
        base_h = reg.get("serving.executable_cache.hits")
        base_m = reg.get("serving.executable_cache.misses")
        cache = ExecutableCache(capacity=1)
        cache.get_or_compile("a", lambda: "exe-a")
        cache.get_or_compile("a", lambda: "exe-a")
        cache.get_or_compile("b", lambda: "exe-b")   # evicts "a"
        assert reg.get("serving.executable_cache.hits") == base_h + 1
        assert reg.get("serving.executable_cache.misses") == base_m + 2
        assert reg.get("serving.executable_cache.evictions") >= 1
        assert reg.get("serving.executable_cache.size") == 1

    def test_metricsz_exposes_executable_cache(self):
        from paddle_tpu.observability.metrics import render_prometheus
        cache = ExecutableCache()
        cache.get_or_compile("m", lambda: "exe")
        text = render_prometheus()
        assert "paddle_tpu_serving_executable_cache_misses_total" in text
        assert "paddle_tpu_serving_executable_cache_size" in text
