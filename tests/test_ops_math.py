"""Math/elementwise/reduction op tests with numpy references
(pattern: reference unittests/test_*_op.py via the OpTest harness)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.ops as ops
from op_test import check_output, check_grad


class TestElementwise:
    def test_add_broadcast(self):
        check_output(paddle.add, np.add,
                     [np.random.rand(3, 4).astype(np.float32),
                      np.random.rand(4).astype(np.float32)])

    def test_binary_family(self):
        a = np.random.rand(2, 3).astype(np.float32) + 0.5
        b = np.random.rand(2, 3).astype(np.float32) + 0.5
        for pfn, nfn in [(paddle.add, np.add), (paddle.subtract, np.subtract),
                         (paddle.multiply, np.multiply), (paddle.divide, np.divide),
                         (paddle.maximum, np.maximum), (paddle.minimum, np.minimum),
                         (paddle.pow, np.power), (paddle.atan2, np.arctan2)]:
            check_output(pfn, nfn, [a, b])

    def test_scalar_ops(self):
        x = paddle.to_tensor([1.0, 2.0, 3.0])
        np.testing.assert_allclose((x + 1).numpy(), [2, 3, 4])
        np.testing.assert_allclose((2 * x).numpy(), [2, 4, 6])
        np.testing.assert_allclose((1 - x).numpy(), [0, -1, -2])
        np.testing.assert_allclose((x / 2).numpy(), [0.5, 1, 1.5])
        np.testing.assert_allclose((x ** 2).numpy(), [1, 4, 9])

    def test_unary_family(self):
        x = np.random.rand(3, 4).astype(np.float32) + 0.1
        for pfn, nfn in [(paddle.exp, np.exp), (paddle.log, np.log),
                         (paddle.sqrt, np.sqrt), (paddle.abs, np.abs),
                         (paddle.tanh, np.tanh), (paddle.sin, np.sin),
                         (paddle.cos, np.cos), (paddle.floor, np.floor),
                         (paddle.ceil, np.ceil), (paddle.square, np.square)]:
            check_output(pfn, nfn, [x], atol=1e-4, rtol=1e-3)

    def test_comparisons(self):
        a = np.array([1.0, 2.0, 3.0], np.float32)
        b = np.array([2.0, 2.0, 2.0], np.float32)
        check_output(paddle.greater_than, np.greater, [a, b])
        check_output(paddle.equal, np.equal, [a, b])
        check_output(paddle.less_equal, np.less_equal, [a, b])

    def test_clip(self):
        x = np.array([-2.0, 0.5, 3.0], np.float32)
        check_output(lambda t: paddle.clip(t, 0.0, 1.0),
                     lambda a: np.clip(a, 0.0, 1.0), [x])


class TestMatmul:
    def test_matmul(self):
        check_output(paddle.matmul, np.matmul,
                     [np.random.rand(3, 4).astype(np.float32),
                      np.random.rand(4, 5).astype(np.float32)])

    def test_matmul_transpose(self):
        a = np.random.rand(4, 3).astype(np.float32)
        b = np.random.rand(5, 4).astype(np.float32)
        check_output(lambda x, y: paddle.matmul(x, y, transpose_x=True, transpose_y=True),
                     lambda x, y: x.T @ y.T, [a, b])

    def test_batched(self):
        check_output(paddle.bmm, np.matmul,
                     [np.random.rand(2, 3, 4).astype(np.float32),
                      np.random.rand(2, 4, 5).astype(np.float32)])

    def test_matmul_grad(self):
        check_grad(paddle.matmul,
                   [np.random.rand(3, 4), np.random.rand(4, 2)], grad_idx=0)
        check_grad(paddle.matmul,
                   [np.random.rand(3, 4), np.random.rand(4, 2)], grad_idx=1)


class TestReduce:
    def test_sum_axes(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        check_output(lambda t: paddle.sum(t), lambda a: np.sum(a).reshape(()), [x])
        check_output(lambda t: paddle.sum(t, axis=1), lambda a: a.sum(1), [x])
        check_output(lambda t: paddle.sum(t, axis=[0, 2], keepdim=True),
                     lambda a: a.sum((0, 2), keepdims=True), [x])

    def test_mean_max_min_prod(self):
        x = np.random.rand(3, 5).astype(np.float32)
        check_output(lambda t: paddle.mean(t, axis=0), lambda a: a.mean(0), [x])
        check_output(lambda t: paddle.max(t, axis=1), lambda a: a.max(1), [x])
        check_output(lambda t: paddle.min(t, axis=1), lambda a: a.min(1), [x])
        check_output(lambda t: paddle.prod(t, axis=0), lambda a: a.prod(0), [x])

    def test_std_var(self):
        x = np.random.rand(4, 6).astype(np.float32)
        check_output(lambda t: paddle.std(t, axis=1),
                     lambda a: a.std(1, ddof=1), [x], atol=1e-4)
        check_output(lambda t: paddle.var(t, axis=1, unbiased=False),
                     lambda a: a.var(1), [x], atol=1e-4)

    def test_logsumexp(self):
        x = np.random.rand(3, 4).astype(np.float32)
        from scipy.special import logsumexp as np_lse
        check_output(lambda t: paddle.logsumexp(t, axis=1),
                     lambda a: np_lse(a, axis=1), [x])

    def test_cumsum(self):
        x = np.random.rand(3, 4).astype(np.float32)
        check_output(lambda t: paddle.cumsum(t, axis=1), lambda a: a.cumsum(1), [x])

    def test_mean_grad(self):
        check_grad(lambda t: paddle.mean(t, axis=1), [np.random.rand(3, 4)])


class TestSearchSort:
    def test_argmax_argsort(self):
        x = np.random.rand(4, 5).astype(np.float32)
        check_output(lambda t: paddle.argmax(t, axis=1), lambda a: a.argmax(1), [x])
        check_output(lambda t: paddle.argsort(t, axis=1), lambda a: a.argsort(1), [x])

    def test_topk(self):
        x = np.array([[1.0, 9.0, 3.0, 7.0]], np.float32)
        v, i = paddle.topk(paddle.to_tensor(x), 2)
        np.testing.assert_allclose(v.numpy(), [[9.0, 7.0]])
        np.testing.assert_array_equal(i.numpy(), [[1, 3]])

    def test_where(self):
        c = np.array([True, False, True])
        a = np.array([1.0, 2.0, 3.0], np.float32)
        b = np.array([9.0, 8.0, 7.0], np.float32)
        check_output(paddle.where, np.where, [c, a, b])

    def test_gather_scatter(self):
        x = np.random.rand(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4])
        check_output(lambda t, i: paddle.gather(t, i, axis=0),
                     lambda a, i: a[i], [x, idx])
        got = paddle.scatter(paddle.to_tensor(np.zeros((4, 2), np.float32)),
                             paddle.to_tensor(np.array([1, 3])),
                             paddle.to_tensor(np.ones((2, 2), np.float32)))
        expected = np.zeros((4, 2), np.float32)
        expected[[1, 3]] = 1
        np.testing.assert_allclose(got.numpy(), expected)

    def test_gather_nd(self):
        x = np.random.rand(3, 4, 5).astype(np.float32)
        idx = np.array([[0, 1], [2, 3]])
        check_output(paddle.gather_nd, lambda a, i: a[tuple(i.T)], [x, idx])

    def test_index_select(self):
        x = np.random.rand(4, 6).astype(np.float32)
        check_output(lambda t, i: paddle.index_select(t, i, axis=1),
                     lambda a, i: a[:, i], [x, np.array([0, 5, 2])])

    def test_unique(self):
        x = np.array([3, 1, 2, 1, 3])
        out = paddle.unique(paddle.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(), [1, 2, 3])

    def test_nonzero_masked_select(self):
        x = paddle.to_tensor(np.array([0.0, 1.5, 0.0, 2.0], np.float32))
        nz = paddle.nonzero(x)
        np.testing.assert_array_equal(nz.numpy(), [[1], [3]])
        ms = paddle.masked_select(x, x > 0)
        np.testing.assert_allclose(ms.numpy(), [1.5, 2.0])


class TestManipulation:
    def test_reshape_transpose(self):
        x = np.arange(24).reshape(2, 3, 4).astype(np.float32)
        check_output(lambda t: paddle.reshape(t, [4, 6]), lambda a: a.reshape(4, 6), [x])
        check_output(lambda t: paddle.transpose(t, [2, 0, 1]),
                     lambda a: a.transpose(2, 0, 1), [x])
        check_output(lambda t: paddle.flatten(t, 1, 2), lambda a: a.reshape(2, 12), [x])

    def test_concat_stack_split(self):
        a = np.random.rand(2, 3).astype(np.float32)
        b = np.random.rand(2, 3).astype(np.float32)
        out = paddle.concat([paddle.to_tensor(a), paddle.to_tensor(b)], axis=1)
        np.testing.assert_allclose(out.numpy(), np.concatenate([a, b], 1))
        out = paddle.stack([paddle.to_tensor(a), paddle.to_tensor(b)], axis=0)
        np.testing.assert_allclose(out.numpy(), np.stack([a, b], 0))
        parts = paddle.split(paddle.to_tensor(a), [1, 2], axis=1)
        np.testing.assert_allclose(parts[0].numpy(), a[:, :1])
        np.testing.assert_allclose(parts[1].numpy(), a[:, 1:])

    def test_squeeze_unsqueeze_tile_expand(self):
        x = np.random.rand(1, 3, 1).astype(np.float32)
        check_output(lambda t: paddle.squeeze(t, axis=0), lambda a: a.squeeze(0), [x])
        check_output(lambda t: paddle.unsqueeze(t, [0]), lambda a: a[None], [x])
        check_output(lambda t: paddle.tile(t, [2, 1, 4]), lambda a: np.tile(a, (2, 1, 4)), [x])
        check_output(lambda t: paddle.expand(t, [5, 3, 2]),
                     lambda a: np.broadcast_to(a, (5, 3, 2)), [x])

    def test_pad(self):
        x = np.random.rand(1, 2, 3, 3).astype(np.float32)
        check_output(lambda t: paddle.pad(t, [1, 1, 2, 2]),
                     lambda a: np.pad(a, [(0, 0), (0, 0), (2, 2), (1, 1)]), [x])

    def test_flip_roll(self):
        x = np.arange(6).reshape(2, 3).astype(np.float32)
        check_output(lambda t: paddle.flip(t, axis=1), lambda a: a[:, ::-1], [x])
        check_output(lambda t: paddle.roll(t, 1, axis=0), lambda a: np.roll(a, 1, 0), [x])

    def test_concat_grad(self):
        a = paddle.to_tensor(np.random.rand(2, 2).astype(np.float32))
        b = paddle.to_tensor(np.random.rand(2, 2).astype(np.float32))
        a.stop_gradient = False
        b.stop_gradient = False
        out = paddle.concat([a, b], axis=0)
        (out * 2).sum().backward()
        np.testing.assert_allclose(a.grad.numpy(), np.full((2, 2), 2.0))
        np.testing.assert_allclose(b.grad.numpy(), np.full((2, 2), 2.0))

    def test_setitem_getitem(self):
        x = paddle.zeros([3, 3])
        x[1] = 5.0
        assert x.numpy()[1].tolist() == [5.0, 5.0, 5.0]
        y = x[0:2]
        assert y.shape == [2, 3]


class TestLinalg:
    def test_cholesky_inverse_det(self):
        a = np.random.rand(3, 3).astype(np.float32)
        spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
        check_output(paddle.linalg.cholesky, np.linalg.cholesky, [spd], atol=1e-4)
        check_output(paddle.linalg.inv, np.linalg.inv, [spd], atol=1e-4)
        check_output(lambda t: paddle.linalg.det(t),
                     lambda x: np.asarray(np.linalg.det(x)), [spd], atol=1e-3)

    def test_solve(self):
        a = np.random.rand(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
        b = np.random.rand(3, 2).astype(np.float32)
        check_output(paddle.linalg.solve, np.linalg.solve, [a, b], atol=1e-4)


class TestCreation:
    def test_basics(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([2], "int32").dtype == np.dtype("int32")
        np.testing.assert_allclose(paddle.full([2], 7.0).numpy(), [7, 7])
        np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(),
                                   np.linspace(0, 1, 5), rtol=1e-6)
        assert paddle.eye(3).numpy().trace() == 3.0
        np.testing.assert_array_equal(paddle.tril(paddle.ones([3, 3])).numpy(),
                                      np.tril(np.ones((3, 3))))

    def test_random_reproducible(self):
        paddle.seed(7)
        a = paddle.rand([4])
        paddle.seed(7)
        b = paddle.rand([4])
        np.testing.assert_allclose(a.numpy(), b.numpy())

    def test_randint_randperm(self):
        r = paddle.randint(0, 10, [100])
        assert r.numpy().min() >= 0 and r.numpy().max() < 10
        p = paddle.randperm(10)
        assert sorted(p.numpy().tolist()) == list(range(10))

    def test_one_hot(self):
        oh = paddle.one_hot(paddle.to_tensor([0, 2]), 3)
        np.testing.assert_allclose(oh.numpy(), [[1, 0, 0], [0, 0, 1]])


class TestRound3MathTail:
    """Numpy checks for the round-3 math additions (reference: logit_op,
    cum_op cummin/logcumsumexp, renorm_op, cos_sim_op, shard_index_op,
    paddle.take/index_add/bucketize/diff/cov)."""

    def test_logit(self):
        x = np.array([0.2, 0.5, 0.8], np.float32)
        out = ops.logit(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, np.log(x / (1 - x)), rtol=1e-6)

    def test_rad2deg_deg2rad_roundtrip(self):
        x = np.array([0.0, np.pi / 2, -np.pi], np.float32)
        deg = ops.rad2deg(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(deg, [0, 90, -180], atol=1e-4)
        back = ops.deg2rad(paddle.to_tensor(deg)).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-6)

    def test_cummin_values_and_indices(self):
        x = np.array([3.0, 1.0, 2.0, 0.5], np.float32)
        vals, idx = ops.cummin(paddle.to_tensor(x))
        np.testing.assert_allclose(vals.numpy(), [3, 1, 1, 0.5])
        np.testing.assert_array_equal(idx.numpy(), [0, 1, 1, 3])
        # ties: the EARLIEST index wins
        vals2, idx2 = ops.cummin(paddle.to_tensor(
            np.array([2.0, 1.0, 1.0, 3.0], np.float32)))
        np.testing.assert_allclose(vals2.numpy(), [2, 1, 1, 1])
        np.testing.assert_array_equal(idx2.numpy(), [0, 1, 1, 1])

    def test_logcumsumexp(self):
        x = np.array([0.1, -2.0, 1.5], np.float32)
        out = ops.logcumsumexp(paddle.to_tensor(x)).numpy()
        ref = np.log(np.cumsum(np.exp(x)))
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_diff_with_prepend(self):
        x = np.array([1.0, 4.0, 9.0], np.float32)
        out = ops.diff(paddle.to_tensor(x),
                       prepend=paddle.to_tensor(
                           np.array([0.0], np.float32))).numpy()
        np.testing.assert_allclose(out, [1, 3, 5])

    def test_take_modes(self):
        x = np.arange(6.0, dtype=np.float32).reshape(2, 3)
        idx = np.array([0, 5, -1], np.int32)
        out = ops.take(paddle.to_tensor(x), paddle.to_tensor(idx)).numpy()
        np.testing.assert_allclose(out, [0, 5, 5])
        wrap = ops.take(paddle.to_tensor(x),
                        paddle.to_tensor(np.array([7], np.int32)),
                        mode="wrap").numpy()
        np.testing.assert_allclose(wrap, [1.0])

    def test_index_add(self):
        x = np.zeros((3, 2), np.float32)
        v = np.ones((2, 2), np.float32)
        out = ops.index_add(paddle.to_tensor(x),
                            paddle.to_tensor(np.array([0, 2], np.int32)),
                            0, paddle.to_tensor(v)).numpy()
        np.testing.assert_allclose(out, [[1, 1], [0, 0], [1, 1]])

    def test_renorm_clamps_norms(self):
        x = np.array([[3.0, 4.0], [0.3, 0.4]], np.float32)
        out = ops.renorm(paddle.to_tensor(x), p=2.0, axis=0,
                         max_norm=1.0).numpy()
        np.testing.assert_allclose(np.linalg.norm(out[0]), 1.0, rtol=1e-4)
        np.testing.assert_allclose(out[1], x[1], rtol=1e-5)  # under limit

    def test_cos_sim(self):
        a = np.array([[1.0, 0.0], [1.0, 1.0]], np.float32)
        b = np.array([[1.0, 0.0], [1.0, 0.0]], np.float32)
        out = ops.cos_sim(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
        np.testing.assert_allclose(out.ravel(), [1.0, 1 / np.sqrt(2)],
                                   rtol=1e-5)

    def test_bucketize(self):
        edges = np.array([1.0, 3.0, 5.0], np.float32)
        x = np.array([0.5, 1.0, 4.0, 6.0], np.float32)
        # searchsorted-left semantics (paddle.bucketize is 1-D
        # searchsorted): equal values insert BEFORE the edge
        out = ops.bucketize(paddle.to_tensor(x),
                            paddle.to_tensor(edges)).numpy()
        np.testing.assert_array_equal(out, [0, 0, 2, 3])
        out_r = ops.bucketize(paddle.to_tensor(x),
                              paddle.to_tensor(edges), right=True).numpy()
        np.testing.assert_array_equal(out_r, [0, 1, 2, 3])

    def test_shard_index_ceiling_convention(self):
        # reference shard_index_op: shard_size = ceil(index_num/nshards)
        x = np.array([1, 6, 12, 19], np.int64)
        out = ops.shard_index(paddle.to_tensor(x), index_num=20, nshards=3,
                              shard_id=0).numpy()
        np.testing.assert_array_equal(out, [1, 6, -1, -1])
        out1 = ops.shard_index(paddle.to_tensor(x), index_num=20, nshards=3,
                               shard_id=1).numpy()
        np.testing.assert_array_equal(out1, [-1, -1, 5, -1])


def test_linalg_toplevel_and_tensor_namespace():
    """paddle.cholesky/inverse/matrix_power + paddle.rank +
    paddle.tensor.* import path (reference: python/paddle/tensor/)."""
    import numpy as np
    import paddle_tpu as paddle

    a = np.array([[4.0, 2.0], [2.0, 3.0]], np.float32)
    c = paddle.cholesky(paddle.to_tensor(a)).numpy()
    np.testing.assert_allclose(c @ c.T, a, rtol=1e-5)
    inv = paddle.inverse(paddle.to_tensor(a)).numpy()
    np.testing.assert_allclose(inv @ a, np.eye(2), atol=1e-5)
    mp = paddle.matrix_power(paddle.to_tensor(a), 3).numpy()
    np.testing.assert_allclose(mp, a @ a @ a, rtol=1e-4)
    assert int(paddle.rank(paddle.to_tensor(a)).numpy()) == 2
    assert paddle.tensor.cholesky is paddle.cholesky
    np.testing.assert_allclose(
        paddle.tensor.rank(paddle.to_tensor(a)).numpy(), 2)


def test_tensor_method_parity_vs_reference():
    """Every method-shaped name in the reference's tensor/__init__.py
    resolves on Tensor (free creation functions excluded — they live at
    the paddle top level and are covered by the top-level parity test)."""
    import re
    import numpy as np
    import paddle_tpu as paddle

    src = open("/root/reference/python/paddle/tensor/__init__.py").read()
    names = []
    for m in re.finditer(r"from \.\w+ import ([\w,\s]+)", src):
        for n in m.group(1).split(","):
            n = n.strip()
            if " as " in n:          # `import flip as reverse`
                n = n.split(" as ")[-1].strip()
            if n:
                names.append(n)
    names += re.findall(r"^\s+'(\w+)',?\s*$", src, re.M)
    free = {"arange", "array_length", "array_read", "array_write",
            "create_array", "empty", "empty_like", "eye", "full",
            "full_like", "linspace", "meshgrid", "ones", "ones_like",
            "rand", "randint", "randn", "randperm", "set_printoptions",
            "to_tensor", "zeros", "zeros_like", "normal", "uniform",
            "standard_normal", "add_n", "diag", "is_tensor", "multiplex",
            "concat", "stack", "broadcast_shape", "shard_index",
            "scatter_nd", "increment", "is_empty"}
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    missing = sorted(set(n for n in names if not n.startswith("_")
                         and n not in free and not hasattr(t, n)))
    assert not missing, missing


def test_tensor_method_tail_semantics():
    import numpy as np
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    np.testing.assert_allclose(x.t().numpy(), x.numpy().T)
    assert int(x.numel().numpy()) == 4
    assert int(x.rank().numpy()) == 2
    np.testing.assert_allclose(x.tril().numpy(), np.tril(x.numpy()))
    np.testing.assert_allclose(
        x.mul(paddle.to_tensor(np.float32(2.0))).numpy(), x.numpy() * 2)
    np.testing.assert_allclose(x.reverse(axis=[0]).numpy(),
                               x.numpy()[::-1])
    import pytest
    with pytest.raises(ValueError, match="t\\(\\) expects"):
        paddle.to_tensor(np.ones((2, 2, 2), np.float32)).t()
    # inplace variants stay on the tape
    y = paddle.to_tensor(np.array([0.5, 1.5], np.float32),
                         stop_gradient=False)
    z = y * 2.0
    z.sqrt_()
    z.sum().backward()
    ref = 2.0 * 0.5 / np.sqrt(np.array([1.0, 3.0]))
    np.testing.assert_allclose(y.grad.numpy(), ref, rtol=1e-5)
    w = paddle.to_tensor(np.array([1.0, -2.0], np.float32))
    w.clip_(min=0.0)
    np.testing.assert_allclose(w.numpy(), [1.0, 0.0])
