"""DataLoader / AMP / metric / hapi Model tests
(pattern: reference unittests/test_dataloader_*, test_amp_*, paddle/tests/test_model.py)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class RangeDS(paddle.io.Dataset):
    def __init__(self, n=20):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.full((3,), i, np.float32), np.int64(i % 2)


class TestDataLoader:
    def test_single_process(self):
        dl = paddle.DataLoader(RangeDS(), batch_size=4)
        batches = list(dl)
        assert len(batches) == 5
        assert batches[0][0].shape == [4, 3]
        np.testing.assert_allclose(batches[0][0].numpy()[:, 0], [0, 1, 2, 3])

    def test_shuffle_and_drop_last(self):
        dl = paddle.DataLoader(RangeDS(18), batch_size=4, shuffle=True,
                               drop_last=True)
        batches = list(dl)
        assert len(batches) == 4
        seen = sorted(int(v) for b in batches for v in b[0].numpy()[:, 0])
        assert len(set(seen)) == 16

    def test_multiprocess_order(self):
        dl = paddle.DataLoader(RangeDS(), batch_size=4, num_workers=2)
        batches = list(dl)
        assert len(batches) == 5
        # in-order delivery despite parallel workers
        np.testing.assert_allclose(batches[1][0].numpy()[:, 0], [4, 5, 6, 7])

    def test_worker_exception_propagates(self):
        class Bad(paddle.io.Dataset):
            def __len__(self):
                return 4

            def __getitem__(self, i):
                raise ValueError("boom")
        with pytest.raises(RuntimeError, match="boom"):
            list(paddle.DataLoader(Bad(), batch_size=2, num_workers=1))

    def test_iterable_dataset(self):
        class It(paddle.io.IterableDataset):
            def __iter__(self):
                for i in range(10):
                    yield np.float32(i)
        dl = paddle.DataLoader(It(), batch_size=4)
        batches = list(dl)
        assert [len(b[0]) for b in batches] == [4, 4, 2]

    def test_samplers(self):
        ds = RangeDS(10)
        bs = paddle.io.BatchSampler(ds, batch_size=3)
        assert len(bs) == 4
        dbs = paddle.io.DistributedBatchSampler(ds, batch_size=2,
                                                num_replicas=2, rank=0)
        idx = [i for b in dbs for i in b]
        assert all(i % 2 == 0 for i in idx)  # rank0 gets even indices

    def test_tensor_dataset_and_split(self):
        xs = paddle.randn([10, 4])
        ys = paddle.arange(10)
        tds = paddle.io.TensorDataset([xs, ys])
        assert len(tds) == 10
        a, b = paddle.io.random_split(tds, [7, 3])
        assert len(a) == 7 and len(b) == 3


class TestAMP:
    def test_autocast_white_black(self):
        with paddle.amp.auto_cast():
            a = paddle.randn([4, 4])
            c = paddle.matmul(a, a)
            assert str(c.dtype) == "bfloat16"
            m = paddle.mean(c)
            assert m.dtype == np.dtype("float32")
        c2 = paddle.matmul(a, a)
        assert c2.dtype == np.dtype("float32")

    def test_autocast_grads_flow(self):
        lin = nn.Linear(4, 4)
        x = paddle.randn([2, 4])
        with paddle.amp.auto_cast():
            loss = lin(x).mean()
        loss.backward()
        assert lin.weight.grad is not None
        assert lin.weight.grad.dtype == np.dtype("float32")

    def test_grad_scaler_skips_inf(self):
        from paddle_tpu.core.tensor import Parameter
        p = Parameter(np.array([1.0], np.float32))
        opt = paddle.optimizer.SGD(0.1, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        loss = (p * np.float32(np.inf)).sum()
        scaler.scale(loss).backward()
        scaler.step(opt)
        scaler.update()
        np.testing.assert_allclose(p.numpy(), [1.0])  # update skipped
        assert scaler._scale < 4.0  # scale decreased

    def test_o2_decorate(self):
        m = nn.Linear(4, 4)
        paddle.amp.decorate(m, level="O2")
        assert str(m.weight.dtype) == "bfloat16"


class TestMetrics:
    def test_accuracy_topk(self):
        acc = paddle.metric.Accuracy(topk=(1, 2))
        pred = paddle.to_tensor(np.array([[0.1, 0.5, 0.4],
                                          [0.6, 0.3, 0.1]], np.float32))
        lab = paddle.to_tensor(np.array([2, 0]))
        acc.update(acc.compute(pred, lab))
        top1, top2 = acc.accumulate()
        assert top1 == 0.5 and top2 == 1.0

    def test_precision_recall(self):
        p = paddle.metric.Precision()
        r = paddle.metric.Recall()
        preds = np.array([0.9, 0.8, 0.2, 0.7])
        labels = np.array([1, 0, 1, 1])
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 2 / 3) < 1e-6
        assert abs(r.accumulate() - 2 / 3) < 1e-6

    def test_auc_perfect(self):
        auc = paddle.metric.Auc()
        auc.update(np.array([0.9, 0.8, 0.1, 0.2]), np.array([1, 1, 0, 0]))
        assert auc.accumulate() > 0.99


class SepDS(paddle.io.Dataset):
    def __init__(self, n=64, seed=0):
        rng = np.random.RandomState(seed)
        self.y = (np.arange(n) % 2).astype(np.int64)
        self.x = (rng.rand(n, 3).astype(np.float32) + self.y[:, None] * 2.0)

    def __len__(self):
        return len(self.y)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class TestHapiModel:
    def _model(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.l1 = nn.Linear(3, 16)
                self.l2 = nn.Linear(16, 2)

            def forward(self, x):
                return self.l2(F.relu(self.l1(x)))
        model = paddle.Model(Net())
        model.prepare(
            paddle.optimizer.Adam(0.05, parameters=model.parameters()),
            nn.CrossEntropyLoss(), paddle.metric.Accuracy())
        return model

    def test_fit_evaluate_predict(self):
        paddle.seed(0)
        model = self._model()
        model.fit(SepDS(), epochs=10, batch_size=16, verbose=0)
        res = model.evaluate(SepDS(seed=1), batch_size=16, verbose=0)
        assert res["acc"] > 0.9, res
        preds = model.predict(SepDS(seed=2), batch_size=16, stack_outputs=True)
        assert preds[0].shape == (64, 2)

    def test_save_load_roundtrip(self, tmp_path):
        model = self._model()
        model.fit(SepDS(), epochs=2, batch_size=16, verbose=0)
        path = str(tmp_path / "ckpt")
        model.save(path)
        assert os.path.exists(path + ".pdparams")
        assert os.path.exists(path + ".pdopt")
        model2 = self._model()
        model2.load(path)
        x = paddle.randn([4, 3])
        np.testing.assert_allclose(model.predict_batch([x]).numpy(),
                                   model2.predict_batch([x]).numpy(),
                                   atol=1e-6)

    def test_early_stopping(self):
        from paddle_tpu.hapi.callbacks import EarlyStopping
        model = self._model()
        es = EarlyStopping(monitor="acc", mode="max", patience=0)
        model.fit(SepDS(), eval_data=SepDS(seed=1), epochs=50, batch_size=16,
                  verbose=0, callbacks=[es])
        assert model.stop_training  # stopped before 50 epochs

    def test_summary(self, capsys):
        model = self._model()
        info = model.summary()
        assert info["total_params"] == 3 * 16 + 16 + 16 * 2 + 2


class TestMetricsAfterPrepareRecompiles:
    def test_late_metrics_get_predictions(self):
        paddle.seed(0)
        net = nn.Linear(4, 3)
        m = paddle.Model(net)
        import paddle_tpu.optimizer as optim
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        m.prepare(opt, nn.CrossEntropyLoss())
        X = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        Y = np.random.RandomState(1).randint(0, 3, (8,)).astype(np.int64)
        m.train_batch([X], [Y])  # compiles WITHOUT predictions
        from paddle_tpu.metric import Accuracy
        m.prepare(opt, nn.CrossEntropyLoss(), metrics=Accuracy())
        loss, mets = m.train_batch([X], [Y])  # must recompile WITH preds
        assert mets and mets[0] is not None


class TestTrainBatchNoUpdate:
    def test_update_false_accumulates_grads_only(self):
        import paddle_tpu.optimizer as optim
        paddle.seed(0)
        net = nn.Linear(4, 2)
        m = paddle.Model(net)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        m.prepare(opt, nn.MSELoss())
        X = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        Y = np.random.RandomState(1).randn(8, 2).astype(np.float32)
        w0 = net.weight.numpy().copy()
        m.train_batch([X], [Y], update=False)
        np.testing.assert_allclose(net.weight.numpy(), w0)  # no update
        assert net.weight.grad is not None
        g1 = net.weight.grad.numpy().copy()
        m.train_batch([X], [Y], update=False)
        np.testing.assert_allclose(net.weight.grad.numpy(), 2 * g1,
                                   rtol=1e-5)  # accumulated
        opt.step()  # the deferred update applies the summed grads
        assert not np.allclose(net.weight.numpy(), w0)


def test_paddle_flops_matches_reference_lenet():
    """paddle.flops via XLA cost analysis (reference:
    hapi/dynamic_flops.py) — the reference's own docstring LeNet table
    sums to 347,560 FLOPs (MAC convention); the compiler-measured count
    must land within 1%."""
    import paddle_tpu as paddle
    from paddle_tpu.vision.models import LeNet
    paddle.seed(0)
    n = paddle.flops(LeNet(), [1, 1, 28, 28])
    assert abs(n - 347560) / 347560 < 0.01, n
    # custom_ops is unnecessary (compiler counts everything): warns
    import warnings
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        paddle.flops(LeNet(), [1, 1, 28, 28], custom_ops={})
    assert not [x for x in w if "custom_ops" in str(x.message)]
    # empty dict is falsy -> no warning; a non-empty one warns
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        paddle.flops(LeNet(), [1, 1, 28, 28],
                     custom_ops={"conv": lambda *a: None})
        assert any("custom_ops" in str(x.message) for x in w)
