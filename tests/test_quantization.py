"""QAT/PTQ (reference: fluid/contrib/slim/quantization — fake_quantize ops
+ ImperativeQuantAware/ImperativePTQ)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu.quantization import (
    fake_quantize_abs_max, fake_channel_wise_quantize_abs_max,
    ImperativeQuantAware, ImperativePTQ, QuantedLinear)


class TestFakeQuant:
    def test_abs_max_roundtrip_and_scale(self):
        x = paddle.to_tensor(np.array([-1.0, 0.5, 0.25], np.float32))
        out, scale = fake_quantize_abs_max(x, bit_length=8)
        assert abs(float(scale.numpy()) - 1.0) < 1e-6
        # values land on the 127-level grid of [-1, 1]
        q = out.numpy() * 127
        np.testing.assert_allclose(q, np.round(q), atol=1e-4)
        np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1 / 127)

    def test_channel_wise_scales(self):
        w = np.array([[1.0, -2.0], [0.5, 4.0]], np.float32)
        out, scales = fake_channel_wise_quantize_abs_max(
            paddle.to_tensor(w), quant_axis=0)
        np.testing.assert_allclose(scales.numpy(), [2.0, 4.0])

    def test_ste_gradient_is_identity(self):
        x = paddle.to_tensor(np.array([0.3, -0.7], np.float32),
                             stop_gradient=False)
        out, _ = fake_quantize_abs_max(x)
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])


class TestQAT:
    def test_quantize_swaps_layers_and_trains(self):
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(nn.functional.relu(self.fc1(x)))

        net = Net()
        ImperativeQuantAware().quantize(net)
        assert isinstance(net._sub_layers["fc1"], QuantedLinear)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4)
                             .astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(1).randn(8, 2)
                             .astype(np.float32))
        losses = []
        for _ in range(5):
            loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]  # STE lets training proceed


class TestPTQ:
    def test_calibrate_and_convert(self):
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, x):
                return self.fc(x)

        net = Net()
        ptq = ImperativePTQ()
        ptq.quantize(net)
        rng = np.random.RandomState(0)
        for _ in range(4):
            net(paddle.to_tensor(rng.randn(8, 4).astype(np.float32)))
        scale = net._sub_layers["fc"]._observer.scale
        assert scale is not None and scale > 0
        ptq.convert(net)
        x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
        out = net(x).numpy()
        # simulated-int8 output stays close to fp32 for in-range data
        ref = (x.numpy() @ net._sub_layers["fc"].weight.numpy()
               + net._sub_layers["fc"].bias.numpy())
        assert np.abs(out - ref).max() < 0.2
