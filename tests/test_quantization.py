"""QAT/PTQ (reference: fluid/contrib/slim/quantization — fake_quantize ops
+ ImperativeQuantAware/ImperativePTQ)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu.quantization import (
    fake_quantize_abs_max, fake_channel_wise_quantize_abs_max,
    ImperativeQuantAware, ImperativePTQ, QuantedLinear)


class TestFakeQuant:
    def test_abs_max_roundtrip_and_scale(self):
        x = paddle.to_tensor(np.array([-1.0, 0.5, 0.25], np.float32))
        out, scale = fake_quantize_abs_max(x, bit_length=8)
        assert abs(float(scale.numpy()) - 1.0) < 1e-6
        # values land on the 127-level grid of [-1, 1]
        q = out.numpy() * 127
        np.testing.assert_allclose(q, np.round(q), atol=1e-4)
        np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1 / 127)

    def test_channel_wise_scales(self):
        w = np.array([[1.0, -2.0], [0.5, 4.0]], np.float32)
        out, scales = fake_channel_wise_quantize_abs_max(
            paddle.to_tensor(w), quant_axis=0)
        np.testing.assert_allclose(scales.numpy(), [2.0, 4.0])

    def test_ste_gradient_is_identity(self):
        x = paddle.to_tensor(np.array([0.3, -0.7], np.float32),
                             stop_gradient=False)
        out, _ = fake_quantize_abs_max(x)
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 1.0])


class TestQAT:
    def test_quantize_swaps_layers_and_trains(self):
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(nn.functional.relu(self.fc1(x)))

        net = Net()
        ImperativeQuantAware().quantize(net)
        assert isinstance(net._sub_layers["fc1"], QuantedLinear)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4)
                             .astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(1).randn(8, 2)
                             .astype(np.float32))
        losses = []
        for _ in range(5):
            loss = ((net(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]  # STE lets training proceed


class TestPTQ:
    def test_calibrate_and_convert(self):
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, x):
                return self.fc(x)

        net = Net()
        ptq = ImperativePTQ()
        ptq.quantize(net)
        rng = np.random.RandomState(0)
        for _ in range(4):
            net(paddle.to_tensor(rng.randn(8, 4).astype(np.float32)))
        scale = net._sub_layers["fc"]._observer.scale
        assert scale is not None and scale > 0
        ptq.convert(net)
        x = paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
        out = net(x).numpy()
        # simulated-int8 output stays close to fp32 for in-range data
        ref = (x.numpy() @ net._sub_layers["fc"].weight.numpy()
               + net._sub_layers["fc"].bias.numpy())
        assert np.abs(out - ref).max() < 0.2


class TestObserverStateDict:
    def _calibrated(self):
        from paddle_tpu.quantization import MovingAverageAbsMaxObserver
        obs = MovingAverageAbsMaxObserver(moving_rate=0.9)
        rng = np.random.RandomState(0)
        for _ in range(3):
            obs.observe(paddle.to_tensor(rng.randn(4, 4).astype(np.float32)))
        return obs

    def test_round_trip_repo_keys(self):
        from paddle_tpu.quantization import MovingAverageAbsMaxObserver
        obs = self._calibrated()
        sd = obs.state_dict()
        assert {"scale", "accum", "state"} <= set(sd)
        fresh = MovingAverageAbsMaxObserver()
        fresh.set_state_dict({k: sd[k] for k in ("scale", "accum", "state")})
        assert abs(fresh.scale - obs.scale) < 1e-6
        assert abs(fresh._accum - obs._accum) < 1e-6
        assert abs(fresh._state - obs._state) < 1e-6

    def test_round_trip_reference_keys(self):
        """A checkpoint written with the reference's persistable-variable
        names (OutScale/InAccum/InState) loads identically."""
        from paddle_tpu.quantization import MovingAverageAbsMaxObserver
        obs = self._calibrated()
        sd = obs.state_dict()
        assert {"OutScale", "InAccum", "InState"} <= set(sd)
        np.testing.assert_allclose(sd["OutScale"], sd["scale"])
        fresh = MovingAverageAbsMaxObserver()
        fresh.set_state_dict(
            {k: sd[k] for k in ("OutScale", "InAccum", "InState")})
        assert abs(fresh.scale - obs.scale) < 1e-6
        assert abs(fresh._state - obs._state) < 1e-6

    def test_wrapper_layer_carries_observer_state(self):
        """QuantedLinear.state_dict embeds the triple; reloading restores
        a calibrated scale on a fresh wrapper."""
        paddle.seed(0)
        lin = nn.Linear(4, 2)
        q = QuantedLinear(lin)
        rng = np.random.RandomState(1)
        for _ in range(3):
            q(paddle.to_tensor(rng.randn(8, 4).astype(np.float32)))
        sd = q.state_dict()
        assert any("_observer." in k for k in sd)
        fresh = QuantedLinear(nn.Linear(4, 2))
        fresh.set_state_dict(sd)
        assert abs(fresh._observer.scale - q._observer.scale) < 1e-6


class TestInt8Execution:
    def test_int8_linear_weight_only_matches_dequant(self):
        from paddle_tpu.quantization import Int8Linear, quantize_weight_int8
        rng = np.random.RandomState(0)
        w = rng.randn(8, 4).astype(np.float32)
        x = rng.randn(5, 8).astype(np.float32)
        lin = Int8Linear.from_float(paddle.to_tensor(w))
        assert lin.weight_q.numpy().dtype == np.int8
        out = lin(paddle.to_tensor(x)).numpy()
        q, s = quantize_weight_int8(w, quant_axis=1)
        ref = x @ (np.asarray(q, np.float32) * np.asarray(s))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)
        # dequantized view stays close to the float master
        assert np.abs(lin.weight.numpy() - w).max() < np.abs(w).max() / 100

    def test_int8_linear_activation_quant_path(self):
        from paddle_tpu.quantization import Int8Linear
        rng = np.random.RandomState(1)
        w = rng.randn(6, 3).astype(np.float32)
        x = rng.randn(4, 6).astype(np.float32)
        lin = Int8Linear.from_float(paddle.to_tensor(w),
                                    act_scale=float(np.abs(x).max()))
        out = lin(paddle.to_tensor(x)).numpy()
        ref = x @ w
        assert np.abs(out - ref).max() < 0.1 * np.abs(ref).max() + 0.05

    def test_ptq_convert_produces_real_int8(self):
        from paddle_tpu.quantization import Int8Linear
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 2)

            def forward(self, x):
                return self.fc(x)

        net = Net()
        ptq = ImperativePTQ()
        ptq.quantize(net)
        rng = np.random.RandomState(0)
        for _ in range(4):
            net(paddle.to_tensor(rng.randn(8, 4).astype(np.float32)))
        ptq.convert(net)
        fc = net._sub_layers["fc"]
        assert isinstance(fc, Int8Linear)
        assert fc.weight_q.numpy().dtype == np.int8
        assert fc._act_scale is not None and fc._act_scale > 0

    def test_save_quantized_model_exports_int8_and_serves(self, tmp_path):
        """PTQ convert -> jit.save -> Predictor: the .pdiparams artifact
        must hold REAL int8 arrays and the loaded program must reproduce
        the converted model's outputs."""
        import pickle
        from paddle_tpu.inference import Config, create_predictor
        from paddle_tpu.static import InputSpec
        paddle.seed(0)

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 3)

            def forward(self, x):
                return self.fc(x)

        net = Net()
        qat = ImperativeQuantAware()
        qat.quantize(net)
        rng = np.random.RandomState(0)
        for _ in range(4):
            net(paddle.to_tensor(rng.randn(8, 4).astype(np.float32)))
        prefix = str(tmp_path / "int8_model")
        qat.save_quantized_model(
            net, prefix, input_spec=[InputSpec([2, 4], "float32", "x")])
        with open(prefix + ".pdiparams", "rb") as f:
            blob = pickle.load(f)
        assert any(p.dtype == np.int8 for p in blob["params"])
        x = rng.randn(2, 4).astype(np.float32)
        ref = net(paddle.to_tensor(x)).numpy()
        pred = create_predictor(Config(prefix))
        out = pred.run([x])
        np.testing.assert_allclose(out[0], ref, rtol=1e-4, atol=1e-4)
