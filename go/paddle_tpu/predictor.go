// Go client for the paddle_tpu C inference API (reference:
// go/paddle/predictor.go over paddle_c_api.h; here over
// csrc/paddle_tpu_capi.h — PTC_PredictorCreate / PTC_Run /
// zero-copy output getters).
//
// Build: compile csrc/capi_shim.cpp into libpaddle_tpu_capi.so first
// (python -c "from paddle_tpu.inference.capi import build_capi;
// print(build_capi())"), then
//
//	CGO_CFLAGS="-I/path/to/repo/csrc" \
//	CGO_LDFLAGS="-L/path/to/so/dir -lpaddle_tpu_capi" go build
//
// See docs/adr/0004-go-client.md for the build/test status in this
// environment.
package paddle_tpu

// #cgo CFLAGS: -I${SRCDIR}/../../csrc
// #cgo LDFLAGS: -lpaddle_tpu_capi
// #include <stdlib.h>
// #include <stdint.h>
// #include <string.h>
// #include "paddle_tpu_capi.h"
import "C"

import (
	"errors"
	"fmt"
	"runtime"
	"unsafe"
)

// DType mirrors PTC_DType.
type DType int32

const (
	Float32 DType = 0
	Int32   DType = 1
	Int64   DType = 2
)

// Tensor is a host-side input/output buffer with a shape.
type Tensor struct {
	Shape []int64
	DType DType
	// exactly one of these is non-nil, matching DType
	F32 []float32
	I32 []int32
	I64 []int64
}

func (t *Tensor) numel() int64 {
	n := int64(1)
	for _, d := range t.Shape {
		n *= d
	}
	return n
}

func (t *Tensor) dataPtr() (unsafe.Pointer, error) {
	switch t.DType {
	case Float32:
		if int64(len(t.F32)) != t.numel() {
			return nil, fmt.Errorf("tensor: F32 has %d elements, shape wants %d",
				len(t.F32), t.numel())
		}
		return unsafe.Pointer(&t.F32[0]), nil
	case Int32:
		if int64(len(t.I32)) != t.numel() {
			return nil, fmt.Errorf("tensor: I32 has %d elements, shape wants %d",
				len(t.I32), t.numel())
		}
		return unsafe.Pointer(&t.I32[0]), nil
	case Int64:
		if int64(len(t.I64)) != t.numel() {
			return nil, fmt.Errorf("tensor: I64 has %d elements, shape wants %d",
				len(t.I64), t.numel())
		}
		return unsafe.Pointer(&t.I64[0]), nil
	}
	return nil, fmt.Errorf("tensor: unknown dtype %d", t.DType)
}

// Predictor wraps a PTC_Predictor handle.
type Predictor struct {
	c *C.PTC_Predictor
}

func lastError() error {
	return errors.New(C.GoString(C.PTC_LastError()))
}

// NewPredictor loads a jit.save artifact (model_prefix.pdmodel /
// .pdiparams pair) and embeds the Python runtime on first use.
func NewPredictor(modelPrefix string) (*Predictor, error) {
	cs := C.CString(modelPrefix)
	defer C.free(unsafe.Pointer(cs))
	p := C.PTC_PredictorCreate(cs)
	if p == nil {
		return nil, lastError()
	}
	pred := &Predictor{c: p}
	runtime.SetFinalizer(pred, (*Predictor).Destroy)
	return pred, nil
}

// Destroy releases the native predictor; safe to call twice.
func (p *Predictor) Destroy() {
	if p.c != nil {
		C.PTC_PredictorDestroy(p.c)
		p.c = nil
	}
}

// NumInputs reports the artifact's input arity.
func (p *Predictor) NumInputs() int {
	return int(C.PTC_GetNumInputs(p.c))
}

// Run executes the model on the given inputs and copies every output
// into fresh Go-owned Tensors (the C buffers are only valid until the
// next Run).
func (p *Predictor) Run(inputs []*Tensor) ([]*Tensor, error) {
	n := len(inputs)
	if n == 0 {
		return nil, errors.New("run: no inputs")
	}
	// cgo pointer rules forbid passing Go arrays that themselves hold Go
	// pointers (cgocheck panics); stage every pointer table and the data
	// buffers in C memory for the duration of the call
	ptrSz := C.size_t(unsafe.Sizeof(unsafe.Pointer(nil)))
	datas := (*[1 << 20]unsafe.Pointer)(C.malloc(C.size_t(n) * ptrSz))
	shapes := (*[1 << 20]*C.int64_t)(C.malloc(C.size_t(n) * ptrSz))
	ndims := (*[1 << 20]C.int)(C.malloc(C.size_t(n) * C.sizeof_int))
	dtypes := (*[1 << 20]C.int)(C.malloc(C.size_t(n) * C.sizeof_int))
	var cbufs []unsafe.Pointer
	freeAll := func() {
		for _, b := range cbufs {
			C.free(b)
		}
		C.free(unsafe.Pointer(datas))
		C.free(unsafe.Pointer(shapes))
		C.free(unsafe.Pointer(ndims))
		C.free(unsafe.Pointer(dtypes))
	}
	for i, t := range inputs {
		ptr, err := t.dataPtr()
		if err != nil {
			freeAll()
			return nil, err
		}
		esize := C.size_t(4)
		if t.DType == Int64 {
			esize = 8
		}
		buf := C.malloc(C.size_t(t.numel()) * esize)
		C.memcpy(buf, ptr, C.size_t(t.numel())*esize)
		cbufs = append(cbufs, buf)
		datas[i] = buf
		shp := C.malloc(C.size_t(len(t.Shape)) * C.sizeof_int64_t)
		C.memcpy(shp, unsafe.Pointer(&t.Shape[0]),
			C.size_t(len(t.Shape))*C.sizeof_int64_t)
		cbufs = append(cbufs, shp)
		shapes[i] = (*C.int64_t)(shp)
		ndims[i] = C.int(len(t.Shape))
		dtypes[i] = C.int(t.DType)
	}
	rc := C.PTC_Run(p.c, &datas[0], &shapes[0], &ndims[0], &dtypes[0],
		C.int(n))
	runtime.KeepAlive(inputs)
	freeAll()
	if rc != 0 {
		return nil, lastError()
	}
	nout := int(C.PTC_GetNumOutputs(p.c))
	outs := make([]*Tensor, nout)
	for i := 0; i < nout; i++ {
		nd := int(C.PTC_GetOutputNumDims(p.c, C.int(i)))
		if nd < 0 {
			return nil, lastError()
		}
		cshape := C.PTC_GetOutputShape(p.c, C.int(i))
		shape := make([]int64, nd)
		total := int64(1)
		for d := 0; d < nd; d++ {
			shape[d] = int64(*(*C.int64_t)(unsafe.Pointer(
				uintptr(unsafe.Pointer(cshape)) +
					uintptr(d)*unsafe.Sizeof(C.int64_t(0)))))
			total *= shape[d]
		}
		dt := DType(C.PTC_GetOutputDType(p.c, C.int(i)))
		data := C.PTC_GetOutputData(p.c, C.int(i))
		if data == nil {
			return nil, lastError()
		}
		t := &Tensor{Shape: shape, DType: dt}
		switch dt {
		case Float32:
			src := unsafe.Slice((*float32)(data), total)
			t.F32 = append([]float32(nil), src...)
		case Int32:
			src := unsafe.Slice((*int32)(data), total)
			t.I32 = append([]int32(nil), src...)
		case Int64:
			src := unsafe.Slice((*int64)(data), total)
			t.I64 = append([]int64(nil), src...)
		default:
			return nil, fmt.Errorf("run: unknown output dtype %d", dt)
		}
		outs[i] = t
	}
	// the finalizer-driven Destroy must not free the C output buffers
	// while the unsafe.Slice copies above are still reading them
	runtime.KeepAlive(p)
	return outs, nil
}
